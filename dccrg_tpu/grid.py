"""The Grid: dccrg's user model on a TPU mesh.

Mirrors the reference's ``Dccrg`` class surface (fluent builder ->
``initialize`` -> iterate local cells / exchange halos / refine / balance,
``dccrg.hpp:472-552, 8104-8230``) with a TPU-native execution model:

* cell payloads are SoA ``[n_devices, rows, ...]`` JAX arrays sharded over a
  1-D ``jax.sharding.Mesh`` (a cell is a row, not an object);
* the payload-type seam — the reference's ``get_mpi_datatype()``
  (``dccrg_get_cell_datatype.hpp:40-339``) — becomes a ``CellSpec`` dict of
  field name -> (shape, dtype);
* grid/refinement metadata stays host-side and replicated, like the
  reference's ``cell_process`` directory (``dccrg.hpp:7196``);
* halo exchanges are precompiled collective schedules (``parallel/halo.py``)
  regenerated per partition epoch.
"""
from __future__ import annotations

import itertools as _itertools
from contextlib import nullcontext as _nullcontext

import numpy as np
import jax
import jax.numpy as jnp

from .core.mapping import Mapping
from .core.topology import Topology
from .core.neighborhood import default_neighborhood, validate_neighborhood
from .core.neighbors import InconsistentGridError, LeafSet
from .geometry import CartesianGeometry, NoGeometry
from .obs.events import timeline as _timeline
from .parallel.epoch import build_epoch
from .parallel.exec_cache import ExecutableCache
from .parallel.halo import HaloExchange
from .parallel.mesh import SHARD_AXIS, make_mesh, shard_spec
from .parallel.shapes import epoch_shape_hints, signature_of
from .parallel.partition import block_partition, hilbert_partition, morton_partition
from .utils.collectives import fetch

__all__ = ["Grid", "CellSpec", "HAS_NO_NEIGHBOR", "HAS_LOCAL_NEIGHBOR_OF",
           "HAS_LOCAL_NEIGHBOR_TO", "HAS_REMOTE_NEIGHBOR_OF",
           "HAS_REMOTE_NEIGHBOR_TO"]

#: field name -> (per-cell shape tuple, dtype); the pytree/dtype analogue of
#: the reference's MPI datatype seam.
CellSpec = dict

#: neighbor-relation criteria bits for ``Grid.get_cells_by_criteria``
#: (reference ``dccrg.hpp:85-142``)
#: source of process-unique ``Grid.grid_id`` values (timeline span
#: separation for concurrent grids — see ``obs.events``)
_GRID_IDS = _itertools.count()

#: reusable no-op context (``nullcontext`` keeps no state, so one
#: instance serves every disabled-timeline dispatch)
_NULL_CTX = _nullcontext()

HAS_NO_NEIGHBOR = 0
HAS_LOCAL_NEIGHBOR_OF = 1 << 0
HAS_LOCAL_NEIGHBOR_TO = 1 << 1
HAS_REMOTE_NEIGHBOR_OF = 1 << 2
HAS_REMOTE_NEIGHBOR_TO = 1 << 3
HAS_LOCAL_NEIGHBOR_BOTH = HAS_LOCAL_NEIGHBOR_OF | HAS_LOCAL_NEIGHBOR_TO
HAS_REMOTE_NEIGHBOR_BOTH = HAS_REMOTE_NEIGHBOR_OF | HAS_REMOTE_NEIGHBOR_TO


class Grid:
    # ------------------------------------------------------------- builder

    def __init__(self):
        self._length = (1, 1, 1)
        self._max_ref_lvl = 0
        self._periodic = (False, False, False)
        self._hood_length = 1
        self._lb_method = "RCB"
        self._geometry_factory = None
        self.initialized = False

    def set_initial_length(self, length) -> "Grid":
        self._assert_uninitialized()
        self._length = tuple(int(v) for v in length)
        return self

    def set_maximum_refinement_level(self, lvl: int) -> "Grid":
        self._assert_uninitialized()
        self._max_ref_lvl = int(lvl)
        return self

    def set_periodic(self, x: bool, y: bool, z: bool) -> "Grid":
        self._assert_uninitialized()
        self._periodic = (bool(x), bool(y), bool(z))
        return self

    def set_neighborhood_length(self, n: int) -> "Grid":
        self._assert_uninitialized()
        if n < 0:
            raise ValueError("neighborhood length must be >= 0")
        self._hood_length = int(n)
        return self

    def set_load_balancing_method(self, method: str) -> "Grid":
        self._assert_uninitialized()
        # normalized once here: compute_partition upper-cases anyway, and
        # initialize's striping dispatch compares verbatim — a lowercase
        # method must not stripe differently from its uppercase spelling
        # (it would also defeat the multi-controller agreement digest)
        self._lb_method = str(method).upper()
        return self

    def set_geometry(self, factory=None, **params) -> "Grid":
        """``factory(mapping, topology) -> geometry``; or a geometry class
        plus keyword params (e.g. ``set_geometry(CartesianGeometry,
        start=..., level_0_cell_length=...)``)."""
        self._assert_uninitialized()
        if factory is None:
            factory = CartesianGeometry
        self._geometry_factory = lambda m, t: factory(mapping=m, topology=t, **params)
        return self

    def _assert_uninitialized(self):
        if self.initialized:
            raise RuntimeError("grid already initialized")

    # ---------------------------------------------------------- initialize

    def initialize(self, mesh=None, n_devices: int | None = None,
                   leaf_set=None) -> "Grid":
        """Create level-0 cells, stripe them over the mesh devices (the
        reference's ``create_level_0_cells``, ``dccrg.hpp:7967-8102``) and
        build all derived state.

        ``leaf_set``: start from an existing leaf-id array instead of the
        level-0 grid — the checkpoint loader's path (the saved set is a
        valid 2:1 forest already, so rebuilding derived state ONCE
        replaces the reference's level-by-level refinement replay,
        ``dccrg.hpp:3647-3716``).  The set is validated: exact domain
        tiling and the 2:1 balance invariant both raise on a corrupt
        file."""
        self._assert_uninitialized()
        self.mesh = mesh if mesh is not None else make_mesh(n_devices=n_devices)
        self.n_devices = self.mesh.devices.size
        self.mapping = Mapping(length=self._length, max_refinement_level=self._max_ref_lvl)
        self.topology = Topology(periodic=self._periodic)
        factory = self._geometry_factory or (lambda m, t: NoGeometry(m, t))
        self.geometry = factory(self.mapping, self.topology)

        self.neighborhoods = {None: default_neighborhood(self._hood_length)}
        self.cell_weights = {}
        self.pin_requests = {}
        from .amr.refinement import AmrQueues

        self.amr = AmrQueues()
        self._last_new_cells = np.zeros(0, dtype=np.uint64)
        self._last_removed_cells = np.zeros(0, dtype=np.uint64)
        self._last_adaptation_delta = None
        self._prev_epoch = None
        #: process-unique id stamped (as ``grid_id``) onto every timeline
        #: span this grid's instrumented seams record, so traces from
        #: concurrent grids stay separable in one merged timeline
        self.grid_id = next(_GRID_IDS)
        self._tl_ctx = None   # cached reusable timeline context frame
        # compiled-schedule cache + recycled table buffers: both survive
        # every epoch rebuild (the whole point — see parallel/shapes.py)
        from .parallel.epoch_delta import TablePool

        self.exec_cache = ExecutableCache()
        self._table_pool = TablePool()
        # ring-size hysteresis hints (parallel/halo.py): shared by every
        # schedule this grid compiles, surviving rebuilds
        self._ring_hints = {}

        if leaf_set is not None:
            cells = np.unique(np.asarray(leaf_set, dtype=np.uint64))
            if len(cells) != len(np.asarray(leaf_set)):
                raise ValueError("leaf_set contains duplicate ids")
            self._validate_leaf_tiling(cells)
        else:
            n0 = int(np.prod(self._length))
            cells = np.arange(1, n0 + 1, dtype=np.uint64)
        # enforced multi-controller agreement on the builder inputs: a
        # controller whose settings diverge would build a different grid
        # and silently desynchronize every later collective; raise on all
        # controllers instead (no-op with one controller)
        from .utils.collectives import assert_agreement

        settings = repr((
            self._length, self._max_ref_lvl, self._periodic,
            self._hood_length, str(self._lb_method).upper(),
            type(self.geometry).__name__,
        )).encode()
        assert_agreement(
            "Grid.initialize settings",
            settings + self.geometry.params_to_file_bytes()
            + (cells.tobytes() if leaf_set is not None else b""),
        )
        if self._lb_method in ("HSFC", "SFC", "HILBERT"):
            owner = hilbert_partition(self.mapping, cells, self.n_devices)
        elif self._lb_method == "MORTON":
            owner = morton_partition(self.mapping, cells, self.n_devices)
        else:
            owner = block_partition(cells, self.n_devices)
        self.leaves = LeafSet(cells=cells, owner=owner.astype(np.int32))
        self.initialized = True
        if leaf_set is not None:
            # the neighbor engine itself rejects many inconsistent sets
            # (no leaf found for a slot); surface those under the same
            # contract as the explicit checks
            try:
                self._rebuild()
            except InconsistentGridError as e:
                raise ValueError(
                    f"leaf_set is not a consistent 2:1 forest: {e}"
                ) from e
            self._validate_two_to_one()
        else:
            self._rebuild()
        return self

    def _validate_leaf_tiling(self, cells):
        """Exact-cover check for a candidate leaf set: the level-weighted
        volumes must tile the domain exactly, plus an explicit
        no-ancestor-overlap screen — the integer volume sum alone could
        be satisfied by a compensating overlap+hole pair, so each
        guarantee is checked on its own rather than delegated to the
        neighbor-engine/2:1 screens."""
        lvl = self.mapping.get_refinement_level(cells)
        if (lvl < 0).any():
            raise ValueError("leaf_set contains invalid cell ids")
        L = self.mapping.max_refinement_level
        counts = np.bincount(lvl.astype(np.int64), minlength=L + 1)
        total = sum(int(c) << (3 * (L - k)) for k, c in enumerate(counts))
        expect = int(np.prod(self._length)) << (3 * L)
        if total != expect:
            raise ValueError(
                "leaf_set does not tile the domain (corrupt checkpoint?)"
            )
        # walk every cell's ancestor chain and verify none is itself in
        # the set (disjointness); with the exact volume sum above this
        # makes the cover exact without relying on downstream checks
        anc = np.unique(cells[lvl > 0])
        while len(anc):
            anc = np.unique(self.mapping.get_parent(anc))
            if np.isin(anc, cells).any():
                raise ValueError(
                    "leaf_set contains both a cell and its ancestor "
                    "(corrupt checkpoint?)"
                )
            anc = anc[self.mapping.get_refinement_level(anc) > 0]

    def _validate_two_to_one(self):
        """Post-build 2:1 balance check from the epoch's neighbor tables:
        every neighbor pair's refinement levels differ by at most one
        (the invariant the neighbor engine assumes)."""
        hood = self.epoch.hoods[None]
        clen = self.epoch.cell_len.astype(np.int64)[..., None]
        nlen = hood.nbr_len.astype(np.int64)
        bad = hood.nbr_valid & (
            (nlen > 2 * clen) | (clen > 2 * nlen)
        )
        if bad.any():
            raise ValueError(
                "leaf_set violates 2:1 balance (corrupt checkpoint?)"
            )

    def _uniform_geometry(self) -> bool:
        """Whether every level-0 cell shares one physical size — the
        precondition for the dense fast path's metric factors (a
        stretched geometry's ``get_level_0_cell_length`` describes only
        its first cell)."""
        return bool(getattr(self.geometry, "uniform_level0", False))

    def _shape_hints(self) -> dict:
        """Bucket-hysteresis hints from the current epoch (empty before
        the first build) — see ``parallel/shapes.py``."""
        return epoch_shape_hints(getattr(self, "epoch", None))

    def shape_signature(self):
        """The current epoch's :class:`~dccrg_tpu.parallel.shapes.
        ShapeSignature` — the identity compiled schedules are keyed by,
        including this grid's held halo ring-size hints (so the
        signature alone predicts executable-cache behavior across a
        rescale or warm restart).  Two epochs with equal signatures
        share every cached executable (``grid.exec_cache``); a rebuild
        that keeps the signature costs zero retraces."""
        return signature_of(self.epoch, self._ring_hints)

    def _harvest_tables(self, old_epoch) -> None:
        """Park a retired epoch's gather-table buffers for reuse by the
        next delta patch — unless the epoch is shared with another grid
        (``copy_structure``), whose tables must stay intact."""
        if old_epoch is None or getattr(old_epoch, "_shared", False):
            return
        # multi-controller put_table hands jitted code the HOST arrays
        # themselves (no device copy) — recycling them would mutate live
        # schedule constants
        if jax.process_count() > 1:
            return
        for h in old_epoch.hoods.values():
            self._table_pool.put(
                (h.nbr_rows, h.nbr_valid, h.nbr_offset, h.nbr_len,
                 h.nbr_slot)
            )
        old_epoch.hoods = {}

    def _rebuild(self):
        """Recompute every derived structure for the current leaf set —
        the analogue of the reference's post-mutation rebuild tail
        (``dccrg.hpp:4063-4111, 10503-10551``).  Timed as the
        ``epoch.build`` phase inside ``build_epoch`` itself."""
        self.epoch = build_epoch(
            self.mapping, self.topology, self.leaves, self.n_devices,
            self.neighborhoods,
            uniform_geometry=self._uniform_geometry(),
            shape_hints=self._shape_hints(),
        )
        self._halo_cache = {}
        self._id_pos_cache = None
        self._unrefine_cache = None

    def _rebuild_incremental(self, old_epoch):
        """Derive the epoch for the current (already mutated) leaf set by
        delta-patching ``old_epoch`` (``parallel/epoch_delta.py``) —
        O(|touched| · K) instead of the full O(N · K) rebuild — falling
        back to ``build_epoch`` (the semantic oracle) whenever the delta
        path declines (closure too large, row-budget jump, dense-path
        flip; see ``epoch_delta.FALLBACK_REASONS``).  Shape hints keep
        the bucketed table shapes sticky, and the retired epoch's table
        buffers are recycled into ``_table_pool`` for the next patch."""
        from .parallel.epoch_delta import build_epoch_delta

        epoch = None
        if old_epoch is not None:
            epoch = build_epoch_delta(
                old_epoch, self.leaves, self.n_devices, self.neighborhoods,
                uniform_geometry=self._uniform_geometry(),
                shape_hints=epoch_shape_hints(old_epoch),
                table_pool=getattr(self, "_table_pool", None),
            )
        if epoch is None:
            self._rebuild()
            return
        self.epoch = epoch
        self._halo_cache = {}
        self._id_pos_cache = None
        self._unrefine_cache = None

    # --------------------------------------------------------- cell views

    def _assert_initialized(self):
        if not self.initialized:
            raise RuntimeError("grid not initialized")

    def _assert_no_staged_lb(self):
        """Structural mutators are forbidden while a staged balance_load
        is pending: the staged epoch reflects the current leaf set."""
        if getattr(self, "_staged_lb", None) is not None:
            raise RuntimeError("a staged balance_load is in progress")

    def get_cells(self) -> np.ndarray:
        """All existing (leaf) cells, ascending id — global view."""
        self._assert_initialized()
        return self.leaves.cells.copy()

    def local_cells(self, device: int | None = None) -> np.ndarray:
        """Cells owned by a device (all devices if None), ascending id."""
        self._assert_initialized()
        if device is None:
            return self.leaves.cells.copy()
        return self.leaves.cells[self.epoch.local_pos[device]]

    def inner_cells(self, device: int, hood_id=None) -> np.ndarray:
        h = self.epoch.hoods[hood_id]
        rows = np.flatnonzero(h.inner_mask[device])
        return self.epoch.cell_ids[device, rows]

    def outer_cells(self, device: int, hood_id=None) -> np.ndarray:
        h = self.epoch.hoods[hood_id]
        rows = np.flatnonzero(h.outer_mask[device])
        return self.epoch.cell_ids[device, rows]

    def remote_cells(self, device: int) -> np.ndarray:
        """Ghost cells held by a device."""
        return self.leaves.cells[self.epoch.ghost_pos[device]]

    def get_owner(self, ids) -> np.ndarray:
        """Owning device of given cells (-1 if not a leaf) — the cell
        directory query (reference ``cell_process``)."""
        pos = self.leaves.position(ids)
        return np.where(pos >= 0, self.leaves.owner[np.maximum(pos, 0)], -1)

    def is_local(self, ids, device: int) -> np.ndarray:
        return self.get_owner(ids) == device

    def get_neighbors_of(self, cell, hood_id=None):
        """(ids, offsets) of a cell's neighbors in reference order."""
        self._assert_initialized()
        pos = int(self.leaves.position(np.uint64(cell)))
        if pos < 0:
            raise ValueError(f"cell {cell} does not exist")
        return self.epoch.hoods[hood_id].lists.row(pos)

    def get_neighbors_to(self, cell, hood_id=None) -> np.ndarray:
        """Unique ids of cells having given cell as neighbor."""
        self._assert_initialized()
        pos = int(self.leaves.position(np.uint64(cell)))
        if pos < 0:
            raise ValueError(f"cell {cell} does not exist")
        h = self.epoch.hoods[hood_id]
        return self.leaves.cells[h.to_src[h.to_start[pos] : h.to_start[pos + 1]]]

    def get_face_neighbors_of(self, cell):
        """(neighbor id, direction) pairs with directions +-1/+-2/+-3 as in
        the reference (``dccrg.hpp:2806-2933``): neighbors sharing a face,
        direction is the axis (1=x, 2=y, 3=z) signed by side."""
        ids, offs = self.get_neighbors_of(cell)
        own_len = int(self.mapping.get_cell_length_in_indices(np.uint64(cell)))
        nbr_len = self.mapping.get_cell_length_in_indices(ids).astype(np.int64)
        out = []
        seen = set()
        for nid, off, nl in zip(ids, offs, nbr_len):
            d = _face_direction(off, own_len, int(nl))
            if d != 0 and (int(nid), d) not in seen:
                seen.add((int(nid), d))
                out.append((np.uint64(nid), d))
        return out

    def get_refinement_level(self, cell) -> int:
        return int(self.mapping.get_refinement_level(np.uint64(cell)))

    def neighbor_criteria(self, device: int, hood_id=None) -> np.ndarray:
        """Bitmask of neighbor-relation criteria per local cell of a device
        (reference bits, ``dccrg.hpp:85-142``)."""
        h = self.epoch.hoods[hood_id]
        lists = h.lists
        owner = self.leaves.owner.astype(np.int64)
        N = len(self.leaves)
        counts = np.diff(lists.start)
        src = np.repeat(np.arange(N), counts)
        bits = np.zeros(N, dtype=np.int32)
        local_nbr = owner[lists.nbr_pos] == owner[src]
        np.bitwise_or.at(bits, src[local_nbr], HAS_LOCAL_NEIGHBOR_OF)
        np.bitwise_or.at(bits, src[~local_nbr], HAS_REMOTE_NEIGHBOR_OF)
        src_to = np.repeat(np.arange(N), np.diff(h.to_start))
        local_to = owner[h.to_src] == owner[src_to]
        np.bitwise_or.at(bits, src_to[local_to], HAS_LOCAL_NEIGHBOR_TO)
        np.bitwise_or.at(bits, src_to[~local_to], HAS_REMOTE_NEIGHBOR_TO)
        return bits[self.epoch.local_pos[device]]

    def get_cells_by_criteria(
        self, device: int, criteria: int, exact_match: bool = False, hood_id=None
    ) -> np.ndarray:
        """Local cells of a device filtered by neighbor-relation criteria
        bits (reference ``get_cells``, ``dccrg.hpp:651-741, 2946-3053``):
        any-bit match by default, all-and-only with ``exact_match``."""
        bits = self.neighbor_criteria(device, hood_id)
        cells = self.local_cells(device)
        if criteria == HAS_NO_NEIGHBOR:
            return cells[bits == 0]
        if exact_match:
            return cells[bits == criteria]
        return cells[(bits & criteria) != 0]

    # ------------------------------------------------ structure sharing

    def copy_structure(self) -> "Grid":
        """A new Grid sharing this grid's decomposition (mapping, topology,
        geometry, leaf set, epoch) but no payload — the analogue of the
        reference's cross-instantiation copy constructor used to hold a
        second payload aligned with the same decomposition
        (``dccrg.hpp:338-438``).  Payloads are separate by construction
        here (states are user-held pytrees), so the copy can even share the
        derived epoch until either grid mutates."""
        g = Grid.__new__(Grid)
        g.__dict__.update(self.__dict__)
        g.cell_weights = dict(self.cell_weights)
        g.pin_requests = dict(self.pin_requests)
        if hasattr(self, "_hier_levels"):
            g._hier_levels = list(self._hier_levels)
            g._hier_options = [dict(o) for o in self._hier_options]
        if hasattr(self, "_partitioning_options"):
            g._partitioning_options = dict(self._partitioning_options)
        from .amr.refinement import AmrQueues

        g.amr = AmrQueues()
        g._halo_cache = dict(self._halo_cache)
        # the shared epoch's tables must never be recycled into either
        # grid's buffer pool while the other may still read them
        if hasattr(self, "epoch"):
            self.epoch._shared = True
        return g

    # -------------------------------------------------- options / getters

    def set_partitioning_option(self, name: str, value) -> "Grid":
        """Record a partitioner option (the reference forwards these as
        Zoltan strings, ``dccrg.hpp:5537-5564``).  The native partitioners
        act on ``LB_METHOD`` (overrides the method), ``IMBALANCE_TOL``
        (max part load as a multiple of the average) and
        ``PHG_CUT_OBJECTIVE``; known Zoltan tuning knobs are documented
        inert and anything unrecognized warns (``parallel/loadbalance.py``).
        Reserved names raise, as in the reference."""
        self._check_reserved_option(name)
        if not hasattr(self, "_partitioning_options"):
            self._partitioning_options = {}
        self._partitioning_options[str(name)] = value
        return self

    @staticmethod
    def _check_reserved_option(name):
        from .parallel.loadbalance import RESERVED_OPTIONS, warn_unknown_option

        if str(name).upper() in RESERVED_OPTIONS:
            raise ValueError(f"option {name!r} is reserved for dccrg")
        warn_unknown_option(name)

    def get_partitioning_options(self, level: int | None = None) -> dict:
        """The recorded global options, or — with ``level`` — the given
        hierarchical level's own options ({} for a nonexistent level)."""
        if level is None:
            return dict(getattr(self, "_partitioning_options", {}))
        opts = getattr(self, "_hier_options", [])
        if not 0 <= int(level) < len(opts):
            return {}
        return dict(opts[int(level)])

    def get_maximum_refinement_level(self) -> int:
        return self.mapping.max_refinement_level

    def get_neighborhood_length(self) -> int:
        return self._hood_length

    def get_load_balancing_method(self) -> str:
        return self._lb_method

    def get_periodicity(self) -> tuple:
        return self.topology.periodic

    def get_total_cells(self) -> int:
        return len(self.leaves)

    def get_local_cell_count(self, device: int) -> int:
        return int(self.epoch.n_local[device])

    def get_ghost_cell_count(self, device: int) -> int:
        return int(self.epoch.n_ghost[device])

    @property
    def length(self):
        return self.mapping.length

    # ------------------------------------------------------------ payloads

    def new_state(self, spec: CellSpec, fill=0):
        """Allocate sharded SoA payload arrays, one per field."""
        self._assert_initialized()
        D, R = self.n_devices, self.epoch.R
        state = {}
        for name, (shape, dtype) in spec.items():
            arr = jnp.full((D, R) + tuple(shape), fill, dtype=dtype)
            state[name] = jax.device_put(arr, shard_spec(self.mesh, arr.ndim))
        return state

    def set_cell_data(self, state, field: str, ids, values):
        """Host-side scatter of per-cell values into a field (init/IO path,
        not the compute path)."""
        ids = np.asarray(ids, dtype=np.uint64)
        pos = self.leaves.position(ids)
        if (pos < 0).any():
            raise ValueError("set_cell_data: non-existing cell")
        dev, row = self.epoch.global_rows(pos)
        host = fetch(state[field]).copy()
        host[dev, row] = values
        new = jax.device_put(
            jnp.asarray(host), shard_spec(self.mesh, host.ndim)
        )
        return {**state, field: new}

    def get_cell_data(self, state, field: str, ids):
        """Host-side gather of per-cell values (verification/IO path)."""
        ids = np.asarray(ids, dtype=np.uint64)
        pos = self.leaves.position(ids)
        if (pos < 0).any():
            raise ValueError("get_cell_data: non-existing cell")
        dev, row = self.epoch.global_rows(pos)
        return fetch(state[field])[dev, row]

    # ---------------------------------------------------------------- halo

    def set_cell_datatype(self, cell_datatype) -> "Grid":
        """Per-cell dynamic payload policy — the reference's
        ``get_mpi_datatype(cell_id, sender, receiver, receiving,
        neighborhood_id)`` seam (``dccrg_get_cell_datatype.hpp:48-125``),
        where a *cell* can vary its transferred content per exchange and
        neighborhood.  ``cell_datatype(field, cell_ids, sender, receiver,
        hood_id) -> bool mask`` selects which of a pair's cells transfer
        ``field``; unselected ghost copies simply keep their previous
        values (exactly the reference's not-included-in-the-datatype
        behavior).  Evaluated once per epoch at schedule compile — the
        trace-once analogue of the reference's per-call dispatch — and
        re-evaluated automatically after AMR/load-balance rebuilds.
        ``None`` clears the policy."""
        self._assert_initialized()
        self._cell_datatype = cell_datatype
        self._halo_cache = {}
        return self

    def halo(self, hood_id=None, cell_datatype=...) -> HaloExchange:
        """Compiled exchange schedule for a neighborhood (cached per
        epoch).  ``cell_datatype`` overrides the grid-level policy for
        this schedule (``...`` = inherit, None = full payloads)."""
        self._assert_initialized()
        installed = getattr(self, "_cell_datatype", None)
        policy = installed if cell_datatype is ... else cell_datatype
        # only the installed policy and the no-policy schedule are
        # cached: an ad-hoc override (often a fresh closure per call)
        # must not grow the cache without bound — it gets a fresh,
        # caller-owned schedule instead
        if policy is None or policy is installed:
            key = (hood_id, policy)
            if key not in self._halo_cache:
                self._halo_cache[key] = HaloExchange(
                    self.epoch, self.epoch.hoods[hood_id], self.mesh,
                    cell_datatype=policy, hood_id=hood_id,
                    exec_cache=self.exec_cache,
                    ring_hints=self._ring_hints,
                )
            return self._halo_cache[key]
        return HaloExchange(
            self.epoch, self.epoch.hoods[hood_id], self.mesh,
            cell_datatype=policy, hood_id=hood_id,
            exec_cache=self.exec_cache,
            ring_hints=self._ring_hints,
        )

    def _span_ctx(self):
        """Timeline context for this grid's instrumented entry points:
        every span recorded inside (halo dispatches, rebuild phases...)
        carries ``grid_id`` — workloads layer ``timeline.context(step=i)``
        on top — so merged traces from concurrent grids stay separable
        (see ``obs.events.EventTimeline.context``).  The frame object is
        cached: the per-dispatch cost is an enabled check plus a list
        push/pop."""
        if not _timeline.enabled:
            return _NULL_CTX
        ctx = self._tl_ctx
        if ctx is None:
            ctx = self._tl_ctx = _timeline.context(grid_id=self.grid_id)
        return ctx

    def update_copies_of_remote_neighbors(self, state, hood_id=None):
        """Blocking ghost refresh (reference ``dccrg.hpp:966-1000``)."""
        with self._span_ctx():
            return self.halo(hood_id)(state)

    def start_remote_neighbor_copy_updates(self, state, hood_id=None):
        """Split-phase start (reference ``dccrg.hpp:5010-5105``): launch
        the ghost-payload collective and return a handle.  The state is
        untouched, so inner-cell compute can proceed with no data
        dependence on the transfer — inside one jitted program XLA
        overlaps them (the reference's overlap pattern,
        ``examples/game_of_life.cpp:124-138``).  Merge with
        ``wait_remote_neighbor_copy_updates(state, handle)``."""
        with self._span_ctx():
            return self.halo(hood_id).start(state)

    def wait_remote_neighbor_copy_updates(self, state, handle=None, hood_id=None):
        """Split-phase wait: merge the ``start`` handle's payload into the
        ghost rows.  The merge is the synchronization — downstream reads of
        ghost rows now depend on the collective, nothing earlier does.
        Without a handle (legacy form) this degrades to a blocking ghost
        refresh."""
        with self._span_ctx():
            if handle is None:
                return self.halo(hood_id)(state)
            return self.halo(hood_id).finish(state, handle)

    # -------------------------------------------------- user neighborhoods

    def add_neighborhood(self, hood_id: int, offsets) -> bool:
        """Add a user-defined neighborhood with its own neighbor lists,
        send/recv schedule and iteration masks (reference
        ``dccrg.hpp:6383-6555``).  As in the reference, the offsets must fit
        inside the default neighborhood so ghost requirements (and hence
        payload layouts) are unchanged; existing states remain valid."""
        self._assert_no_staged_lb()
        self._assert_initialized()
        # enforced agreement BEFORE any early-out: every controller must
        # attempt the same registration or all of them fail loudly
        from .utils.collectives import assert_agreement

        assert_agreement(
            f"add_neighborhood({hood_id})",
            np.int64(-1 if hood_id is None else hood_id).tobytes()
            + np.asarray(offsets, dtype=np.int64).tobytes(),
        )
        if hood_id in self.neighborhoods or hood_id is None:
            return False
        offs = validate_neighborhood(offsets)
        n = self._hood_length
        if n == 0:
            default = {tuple(o) for o in self.neighborhoods[None].tolist()}
            if not all(tuple(o) in default for o in offs.tolist()):
                return False
        else:
            if np.abs(offs).max() > n:
                return False
        self.neighborhoods[hood_id] = offs
        self._rebuild()
        return True

    def remove_neighborhood(self, hood_id: int) -> bool:
        from .utils.collectives import assert_agreement

        assert_agreement(
            f"remove_neighborhood({hood_id})",
            np.int64(-1 if hood_id is None else hood_id).tobytes(),
        )
        if hood_id is None or hood_id not in self.neighborhoods:
            return False
        del self.neighborhoods[hood_id]
        self._rebuild()
        return True

    # ------------------------------------------------------- load balancing

    def set_cell_weight(self, cell, weight: float) -> bool:
        """Per-cell load-balance weight (reference ``dccrg.hpp:6210-6276``;
        default weight 1)."""
        self._assert_no_staged_lb()
        if not self.leaves.exists(np.uint64(cell)):
            return False
        self.cell_weights[int(cell)] = float(weight)
        return True

    def get_cell_weight(self, cell) -> float:
        return self.cell_weights.get(int(cell), 1.0)

    def pin(self, cell, device: int | None = None) -> bool:
        """Pin a cell to a device across load balances (its current owner if
        ``device`` is None) — reference ``dccrg.hpp:5832-6010``."""
        pos = int(self.leaves.position(np.uint64(cell)))
        if pos < 0:
            return False
        if device is None:
            device = int(self.leaves.owner[pos])
        if not 0 <= device < self.n_devices:
            return False
        self.pin_requests[int(cell)] = int(device)
        return True

    def unpin(self, cell) -> bool:
        if not self.leaves.exists(np.uint64(cell)):
            return False
        self.pin_requests.pop(int(cell), None)
        return True

    def unpin_all_cells(self) -> bool:
        self.pin_requests.clear()
        return True

    def add_partitioning_level(self, processes_per_part: int):
        """Hierarchical partitioning level (reference Zoltan HIER,
        ``dccrg.hpp:5566-5608``): devices are grouped in blocks of
        ``processes_per_part`` (e.g. chips per ICI-connected slice); cells
        are first balanced over groups, then within each group.  Multiple
        calls nest: each later level subdivides the previous level's
        groups (e.g. ``add_partitioning_level(4)`` then ``(2)`` on 8
        devices gives a 2x2x2 hierarchy: slices of 4, pairs of 2, then
        single devices).

        Each level starts with the reference's default per-level options
        (LB_METHOD=HYPERGRAPH, PHG_CUT_OBJECTIVE=CONNECTIVITY,
        ``dccrg.hpp:5600-5605``); override with
        ``add_partitioning_option(level, ...)``."""
        if int(processes_per_part) < 1:
            raise ValueError(
                "must assign at least 1 process to a hierarchical "
                "partitioning level"
            )
        if not hasattr(self, "_hier_levels"):
            self._hier_levels = []
            self._hier_options = []
        self._hier_levels.append(int(processes_per_part))
        self._hier_options.append({
            "LB_METHOD": "HYPERGRAPH",
            "PHG_CUT_OBJECTIVE": "CONNECTIVITY",
        })

    def remove_partitioning_level(self, level: int):
        """Remove the given hierarchical partitioning level (0-based);
        does nothing if it doesn't exist (``dccrg.hpp:5610-5648``)."""
        levels = getattr(self, "_hier_levels", [])
        if 0 <= int(level) < len(levels):
            del levels[int(level)]
            del self._hier_options[int(level)]

    def add_partitioning_option(self, level: int, name: str, value):
        """Add (or overwrite) a partitioning option for the given
        hierarchical level; does nothing if the level doesn't exist,
        raises on reserved names (``dccrg.hpp:5650-5706``)."""
        self._check_reserved_option(name)
        opts = getattr(self, "_hier_options", [])
        if 0 <= int(level) < len(opts):
            opts[int(level)][str(name)] = value

    def remove_partitioning_option(self, level: int, name: str):
        """Remove a partitioning option from the given hierarchical
        level; does nothing if the level or option doesn't exist
        (``dccrg.hpp:5708-5744``)."""
        opts = getattr(self, "_hier_options", [])
        if 0 <= int(level) < len(opts):
            opts[int(level)].pop(str(name), None)

    def balance_load(self, use_zoltan: bool = True):
        """Repartition cells (method from ``set_load_balancing_method``,
        pins override) and rebuild all derived state — the reference's
        3-phase ``balance_load`` (``dccrg.hpp:1024-1044, 3741-4147``)
        collapsed into one host-side step; carry payloads over with
        ``remap_state`` (pure ownership moves keep every cell's value).
        For chunked payload migration use ``initialize_balance_load`` /
        ``continue_balance_load`` / ``finish_balance_load``."""
        self._assert_initialized()
        if getattr(self, "_staged_lb", None) is not None:
            raise RuntimeError("a staged balance_load is in progress")
        from .obs import metrics

        with self._span_ctx(), metrics.phase("loadbalance.migrate"):
            owner = self._compute_new_owner(use_zoltan)
            self._lb_telemetry(self.leaves.owner, owner)
            self._last_new_cells = np.zeros(0, dtype=np.uint64)
            self._last_removed_cells = np.zeros(0, dtype=np.uint64)
            # load balancing cancels pending adaptation (reference:
            # requests are lost after balance_load, dccrg.hpp:2666-2668)
            self.amr.clear()
            if np.array_equal(owner, self.leaves.owner):
                # no cell moved: every derived table is still valid, skip
                # the (expensive) epoch rebuild; remap_state degenerates
                # to the identity (checkpoint reload hits this on its
                # post-replay balance when the partitioner reproduces the
                # current owners)
                self._prev_epoch = None
                return self
            old_epoch = self.epoch
            self.leaves = LeafSet(cells=self.leaves.cells, owner=owner)
            self._rebuild_incremental(old_epoch)
            self._prev_epoch = _EpochCarry(old_epoch)
            self._harvest_tables(old_epoch)
        return self

    def _lb_telemetry(self, old_owner, new_owner):
        """Record one repartition: cells whose owner changes and the load
        imbalance (max device load over the mean) before/after."""
        from .obs import metrics

        if not metrics.enabled:
            return
        metrics.inc("loadbalance.migrations")
        metrics.inc(
            "loadbalance.cells_migrated",
            int((np.asarray(old_owner) != np.asarray(new_owner)).sum()),
        )

        def imbalance(owner):
            counts = np.bincount(
                np.asarray(owner, dtype=np.int64), minlength=self.n_devices
            )
            avg = counts.mean()
            return float(counts.max() / avg) if avg > 0 else 1.0

        metrics.gauge("loadbalance.imbalance_before", imbalance(old_owner))
        metrics.gauge("loadbalance.imbalance_after", imbalance(new_owner))

    def _hierarchical_partition(self, method, weights, hier, options=None):
        """Multi-level partition over a device hierarchy (reference HIER,
        ``dccrg.hpp:5566-5798``): split cells over groups of ``hier[0]``
        devices (DCN level), then recurse into each group with the
        remaining levels, ending at single devices (ICI level).

        ``hier`` is a list of ``(processes_per_part, level_options)``
        pairs: each level's split runs under its own merged options
        (global ``set_partitioning_option`` values overlaid with the
        level's own, so a level-local IMBALANCE_TOL or LB_METHOD wins),
        mirroring the reference's per-level Zoltan option sets.  Levels
        exhausted with devices remaining fall through to the grid's
        global method."""
        from .parallel.loadbalance import compute_partition

        options = options or {}
        hier = [(int(per), dict(lv_opts or {})) for per, lv_opts in hier]

        def level_method(lv_opts):
            merged = {str(k).upper(): v for k, v in options.items()}
            merged.update({str(k).upper(): v for k, v in lv_opts.items()})
            return str(merged.get("LB_METHOD", method)).upper(), merged

        # one adjacency for the whole hierarchy, restricted per group —
        # built only if some level (or the fall-through method, which the
        # global LB_METHOD option can itself override) needs it
        methods_used = [level_method(lv_opts)[0] for _, lv_opts in hier]
        methods_used.append(level_method({})[0])
        adjacency = None
        if any(m in ("GRAPH", "HYPERGRAPH") for m in methods_used):
            from .parallel.graph import grid_adjacency

            adjacency = grid_adjacency(self)

        owner = np.zeros(len(self.leaves), dtype=np.int32)

        def recurse(sub, idx, w, levels, first, n_devices, adj):
            if n_devices <= 1 or len(idx) == 0:
                owner[idx] = first
                return
            if not levels:
                ft_method, ft_options = level_method({})
                owner[idx] = first + compute_partition(
                    ft_method, sub, n_devices, w, ft_options, adj
                )
                return
            lv_method, lv_options = level_method(levels[0][1])
            per = max(1, min(levels[0][0], n_devices))
            # groups of `per` devices plus a remainder group when per does
            # not divide the device count — no device may be left idle
            group_sizes = [per] * (n_devices // per)
            if n_devices % per:
                group_sizes.append(n_devices % per)
            if len(group_sizes) == 1:
                recurse(sub, idx, w, levels[1:], first, n_devices, adj)
                return
            # partition at device granularity, then merge consecutive parts
            # into groups proportional to each group's device count (equal
            # n_groups-way cuts would misweight a remainder group)
            fine = compute_partition(
                lv_method, sub, n_devices, w, lv_options, adj
            )
            bounds = np.cumsum([0] + group_sizes)
            group = np.searchsorted(bounds, fine, side="right") - 1
            for gi, n_dev_g in enumerate(group_sizes):
                sel = np.flatnonzero(group == gi)
                if not len(sel):
                    continue
                sub_adj = None
                if adj is not None:
                    from .parallel.graph import restrict_adjacency

                    sub_adj = restrict_adjacency(adj[0], adj[1], sel)
                recurse(
                    _SubGridView(sub, sel),
                    idx[sel],
                    w[sel] if w is not None else None,
                    levels[1:],
                    first + int(bounds[gi]),
                    n_dev_g,
                    sub_adj,
                )

        recurse(
            self,
            np.arange(len(self.leaves)),
            weights,
            list(hier),
            0,
            self.n_devices,
            adjacency,
        )
        return owner

    def _compute_new_owner(self, use_zoltan: bool) -> np.ndarray:
        """The new per-leaf owner array: multi-controller pin/weight
        agreement, partitioner, pin overrides."""
        from .parallel.loadbalance import compute_partition
        from .utils.collectives import sync_partition_inputs

        # multi-controller agreement on pins/weights before partitioning
        # (update_pin_requests All_Gather, dccrg.hpp:8297-8340) — a
        # transient merged view; this controller's own dicts stay local.
        # Identity under the single controller.
        all_pins, all_weights = sync_partition_inputs(
            self.pin_requests, self.cell_weights
        )

        weights = None
        if all_weights:
            weights = np.ones(len(self.leaves))
            for c, w in all_weights.items():
                p = int(self.leaves.position(np.uint64(c)))
                if p >= 0:
                    weights[p] = w

        method = self._lb_method if use_zoltan else "NONE"
        options = self.get_partitioning_options()
        hier = getattr(self, "_hier_levels", None)
        if hier and method.upper() != "NONE":
            hier_opts = getattr(self, "_hier_options", [{} for _ in hier])
            owner = self._hierarchical_partition(
                method, weights, list(zip(hier, hier_opts)), options
            )
        else:
            owner = compute_partition(
                method, self, self.n_devices, weights, options
            )

        # pins override the partitioner (make_new_partition,
        # dccrg.hpp:8417-8580)
        for c, d in all_pins.items():
            p = int(self.leaves.position(np.uint64(c)))
            if p >= 0:
                owner[p] = d
        return np.asarray(owner).astype(np.int32)

    def initialize_balance_load(self, use_zoltan: bool = True):
        """Phase 1 of the reference's split balance_load
        (``dccrg.hpp:3741-3884``): compute the new partition and build the
        new derived state WITHOUT touching the live grid — queries and
        stencils keep working on the old layout while payload chunks
        migrate through ``continue_balance_load``."""
        self._assert_initialized()
        if getattr(self, "_staged_lb", None) is not None:
            raise RuntimeError("a staged balance_load is in progress")
        from .obs import metrics

        with self._span_ctx(), metrics.phase("loadbalance.migrate"):
            owner = self._compute_new_owner(use_zoltan)
            self._lb_telemetry(self.leaves.owner, owner)
            # load balancing cancels pending adaptation
            # (dccrg.hpp:2666-2668)
            self.amr.clear()
            if np.array_equal(owner, self.leaves.owner):
                self._staged_lb = {"noop": True}
                return self
            new_leaves = LeafSet(cells=self.leaves.cells, owner=owner)
            # the staged epoch is a pure ownership migration off the live
            # one: the delta path reuses every neighbor relation and
            # re-derives only the owner-dependent tables
            from .parallel.epoch_delta import build_epoch_delta

            new_epoch = build_epoch_delta(
                self.epoch, new_leaves, self.n_devices, self.neighborhoods,
                uniform_geometry=self._uniform_geometry(),
                shape_hints=self._shape_hints(),
                table_pool=getattr(self, "_table_pool", None),
            )
            if new_epoch is None:
                new_epoch = build_epoch(
                    self.mapping, self.topology, new_leaves, self.n_devices,
                    self.neighborhoods,
                    uniform_geometry=self._uniform_geometry(),
                    shape_hints=self._shape_hints(),
                )
        self._staged_lb = {
            "noop": False,
            "leaves": new_leaves,
            "epoch": new_epoch,
            "staged": None,
            "host_old": None,
            "done": 0,
        }
        return self

    def continue_balance_load(self, state=None, max_cells=None) -> bool:
        """Phase 2, repeatable (``dccrg.hpp:3892-3934``): migrate the next
        ``max_cells`` leaves' payload rows into the staged new layout.
        Each call reads from the state PASSED TO IT (only the chunk's rows
        leave the device), so callers overlapping migration with compute
        must pass the state they want captured for that chunk — the same
        contract as the reference, which ships whatever is in cell_data at
        continue time.  Returns True while more cells remain; no ``state``
        means nothing to move (False)."""
        st = getattr(self, "_staged_lb", None)
        if st is None:
            raise RuntimeError("initialize_balance_load has not been called")
        if st.get("noop") or state is None:
            return False
        N = len(self.leaves)
        old, new = self.epoch, st["epoch"]
        if st["staged"] is None:
            st["staged"] = {
                k: np.zeros(
                    (new.n_devices, new.R) + tuple(v.shape[2:]),
                    np.dtype(v.dtype),
                )
                for k, v in state.items()
            }
        lo = st["done"]
        hi = N if max_cells is None else min(lo + int(max_cells), N)
        if lo < hi:
            from .obs import metrics

            metrics.inc("loadbalance.staged_rows", hi - lo)
            pos = np.arange(lo, hi)
            d_old, r_old = old.leaves.owner[pos], old.row_of[pos]
            d_new, r_new = new.leaves.owner[pos], new.row_of[pos]
            for k, arr in state.items():
                # per-chunk capture from the state passed to THIS call
                # (the split-phase contract); the eager gather runs SPMD
                # on every controller, fetch() brings the chunk home
                st["staged"][k][d_new, r_new] = fetch(arr[d_old, r_old])
            st["done"] = hi
        return hi < N

    def finish_balance_load(self, state=None):
        """Phase 3 (``dccrg.hpp:3942-4147``): commit the new directory and
        derived state.  Remaining chunks are drained from ``state`` first;
        returns the migrated state when payloads were staged, else the
        grid.  A partial migration with no ``state`` to finish from is an
        error (the staged copy would silently be incomplete)."""
        st = getattr(self, "_staged_lb", None)
        if st is None:
            raise RuntimeError("initialize_balance_load has not been called")
        if st.get("noop"):
            self._staged_lb = None
            self._prev_epoch = None
            self._last_new_cells = np.zeros(0, dtype=np.uint64)
            self._last_removed_cells = np.zeros(0, dtype=np.uint64)
            return state if state is not None else self
        if state is not None:
            while self.continue_balance_load(state):
                pass
        elif st["staged"] is not None and st["done"] < len(self.leaves):
            raise RuntimeError(
                "migration is partial; pass the state to finish_balance_load"
            )
        self._staged_lb = None
        old_epoch = self.epoch
        self._prev_epoch = _EpochCarry(old_epoch)
        self._last_new_cells = np.zeros(0, dtype=np.uint64)
        self._last_removed_cells = np.zeros(0, dtype=np.uint64)
        self.leaves = st["leaves"]
        self.epoch = st["epoch"]
        self._harvest_tables(old_epoch)
        self._halo_cache = {}
        self._id_pos_cache = None
        if st["staged"] is None:
            return self
        return {
            k: jax.device_put(jnp.asarray(v), shard_spec(self.mesh, v.ndim))
            for k, v in st["staged"].items()
        }

    # ------------------------------------------------------------------ AMR

    def _leaf_level(self, cell) -> int:
        pos = int(self.leaves.position(np.uint64(cell)))
        if pos < 0:
            return -1
        return self.mapping.refinement_level_of(int(cell))

    def refine_completely(self, cell) -> bool:
        """Queue a cell for refinement into 8 children at the next
        ``stop_refining`` (reference ``dccrg.hpp:2434-2532``)."""
        cell = int(cell)
        lvl = self._leaf_level(cell)
        if lvl < 0:
            return False
        if lvl == self.mapping.max_refinement_level:
            self.dont_unrefine(cell)
            return True
        if cell in self.amr.not_to_refine:
            return False
        ids = None
        if self.amr.not_to_refine:
            ids, _ = self.get_neighbors_of(cell)
            n_lvl = self.mapping.get_refinement_level(ids)
            if any(
                int(n) in self.amr.not_to_refine
                for n in ids[n_lvl < lvl]
            ):
                return False
        self.amr.to_refine.add(cell)
        # cancel conflicting unrefines: own siblings + same-or-coarser
        # neighbors' siblings (skipped when no unrefines are pending — the
        # mass-refinement fast path)
        if self.amr.to_unrefine:
            if ids is None:
                ids, _ = self.get_neighbors_of(cell)
            both = np.concatenate(
                [[np.uint64(cell)], ids, self.get_neighbors_to(cell)]
            ).astype(np.uint64)
            nl = self.mapping.get_refinement_level(both)
            cand = both[nl <= lvl]
            sibs = self.mapping.get_siblings(cand).reshape(-1)
            self.amr.to_unrefine.difference_update(sibs.tolist())
        return True

    def unrefine_completely(self, cell) -> bool:
        """Queue a cell's sibling family for replacement by its parent
        (reference ``dccrg.hpp:2560-2655``)."""
        cell = int(cell)
        lvl = self._leaf_level(cell)
        if lvl < 0:
            return False
        if lvl == 0:
            return True
        # per-sibling checks in the reference's order: has-children first
        # (False), then refine-queued/vetoed (True)
        siblings = self.mapping.siblings_of(cell)
        is_leaf = self.leaves.exists(np.asarray(siblings, dtype=np.uint64))
        for sib, leaf in zip(siblings, is_leaf):
            if not leaf:
                return False
            if sib in self.amr.to_refine or sib in self.amr.not_to_unrefine:
                return True
        # family already queued — hoisted above the expensive
        # parent-neighborhood search; a queued family always reaches a
        # True return below (queuing excludes child-bearing/refining/
        # vetoed siblings within an epoch), so the early exit preserves
        # the reference's return values
        if not self.amr.to_unrefine.isdisjoint(siblings):
            return True
        # parent's would-be neighborhood must not contain too-fine cells;
        # the neighbor structure is static per epoch, so it is computed
        # ONCE for every candidate parent in one vectorized search and
        # cached (only the to_refine membership check is per-call)
        too_fine, same_lvl_nbrs = self._unrefine_parent_info(
            self.mapping.parent_of(cell)
        )
        if too_fine:
            return True  # no-op: neighbor more than one level finer
        if not self.amr.to_refine.isdisjoint(same_lvl_nbrs):
            return True  # a would-be same-size neighbor is being refined
        self.amr.to_unrefine.add(cell)
        return True

    def _build_unrefine_cache(self):
        """Per-epoch vectorized answers for the unrefine parent-hood
        checks: ONE neighbor search over every candidate parent (the
        per-family scalar search used to dominate unrefinement request
        storms).  Returns ``(epoch, parents(sorted), too_fine_all,
        fcells, fstart)`` — per-parent answers resolve lazily by
        searchsorted; shared by the scalar and bulk request paths."""
        cache = getattr(self, "_unrefine_cache", None)
        if cache is not None and cache[0] is self.epoch:
            return cache
        from .amr.refinement import _find_for_nonleaves

        lvl = self.mapping.get_refinement_level(self.leaves.cells)
        finer = self.leaves.cells[lvl > 0]
        parents = np.unique(self.mapping.get_parent(finer))
        if len(parents):
            plists = _find_for_nonleaves(
                self.mapping, self.topology, self.leaves,
                parents, self.neighborhoods[None],
            )
            p_lvl = self.mapping.get_refinement_level(parents)
            counts = np.diff(plists.start)
            src = np.repeat(np.arange(len(parents)), counts)
            pos = plists.nbr_pos
            neg = (pos < 0).astype(np.int64)
            cum = np.concatenate(([0], np.cumsum(neg)))
            too_fine_all = (
                cum[plists.start[1:]] - cum[plists.start[:-1]]
            ) > 0
            n_lvl = np.where(
                pos >= 0,
                self.mapping.get_refinement_level(
                    self.leaves.cells[np.maximum(pos, 0)]
                ),
                -1,
            )
            fine_mask = n_lvl == p_lvl[src] + 1
            fsrc = src[fine_mask]
            fcells = self.leaves.cells[pos[fine_mask]]
            fcounts = np.bincount(fsrc, minlength=len(parents))
            fstart = np.concatenate(([0], np.cumsum(fcounts)))
        else:
            too_fine_all = np.zeros(0, dtype=bool)
            fcells = np.zeros(0, dtype=np.uint64)
            fstart = np.zeros(1, dtype=np.int64)
        cache = (self.epoch, parents, too_fine_all, fcells, fstart)
        self._unrefine_cache = cache
        return cache

    def _unrefine_parent_info(self, parent: int):
        """(too_fine, ids of the parent's would-be neighbors one level
        finer than it) for a candidate parent, from the per-epoch
        cache."""
        _, parents, too_fine_all, fcells, fstart = (
            self._build_unrefine_cache()
        )
        i = int(np.searchsorted(parents, np.uint64(parent)))
        if i >= len(parents) or parents[i] != np.uint64(parent):
            return True, frozenset()
        return (
            bool(too_fine_all[i]),
            set(fcells[fstart[i]:fstart[i + 1]].tolist()),
        )

    def dont_refine(self, cell) -> bool:
        cell = int(cell)
        lvl = self._leaf_level(cell)
        if lvl < 0:
            return False
        if lvl == self.mapping.max_refinement_level:
            return True
        self.amr.to_refine.discard(cell)
        self.amr.not_to_refine.add(cell)
        return True

    def dont_unrefine(self, cell) -> bool:
        cell = int(cell)
        lvl = self._leaf_level(cell)
        if lvl < 0:
            return False
        if lvl == 0:
            return True
        siblings = self.mapping.siblings_of(cell)
        if any(s in self.amr.not_to_unrefine for s in siblings):
            return True
        for s in siblings:
            self.amr.to_unrefine.discard(s)
        self.amr.not_to_unrefine.add(cell)
        return True

    # ------------------------------------------------- bulk request storms

    def _set_array(self, s):
        return np.fromiter(s, dtype=np.uint64, count=len(s))

    def refine_completely_many(self, cells) -> np.ndarray:
        """Vectorized ``refine_completely`` over an id array: identical
        final queue state and per-cell returns to calling the scalar API
        in order.  The vectorized form engages when no unrefines are
        pending and no refine vetoes exist (the mass-storm shape of
        adaptation drivers, where the scalar loop's per-request checks
        all degenerate); otherwise it falls back to the scalar loop."""
        ids = np.asarray(cells, dtype=np.uint64).reshape(-1)
        if len(ids) == 0:
            return np.zeros(0, dtype=bool)
        if self.amr.not_to_refine or self.amr.to_unrefine:
            return np.array(
                [self.refine_completely(int(c)) for c in ids], dtype=bool
            )
        pos = self.leaves.position(ids)
        exists = pos >= 0
        lvl = self.mapping.get_refinement_level(ids)
        at_max = exists & (lvl == self.mapping.max_refinement_level)
        if at_max.any():
            self.dont_unrefine_many(ids[at_max])
        mid = exists & ~at_max
        self.amr.to_refine.update(int(c) for c in ids[mid])
        return exists

    def unrefine_completely_many(self, cells) -> np.ndarray:
        """Vectorized ``unrefine_completely`` over an id array: identical
        final queue state and returns to the scalar loop (a pure
        unrefine storm's queue interactions are family-local, so every
        check vectorizes: sibling leaf-ness, refine-queued/vetoed
        siblings, already-queued families, the cached parent-hood
        answers, and first-requested-sibling-per-family dedupe)."""
        ids = np.asarray(cells, dtype=np.uint64).reshape(-1)
        out = np.zeros(len(ids), dtype=bool)
        if len(ids) == 0:
            return out
        pos = self.leaves.position(ids)
        exists = pos >= 0
        lvl = np.where(exists, self.mapping.get_refinement_level(ids), 0)
        out[exists & (lvl == 0)] = True
        idx = np.flatnonzero(exists & (lvl > 0))
        if not len(idx):
            return out
        sibs = self.mapping.get_siblings(ids[idx]).reshape(len(idx), 8)
        sib_leaf = self.leaves.exists(sibs.reshape(-1)).reshape(-1, 8)
        # one to_refine conversion per storm, shared with the parent-hood
        # check below
        tr_arr = (self._set_array(self.amr.to_refine)
                  if self.amr.to_refine else None)
        # the scalar loop walks siblings IN ORDER: the first non-leaf
        # sibling returns False, but a refine-queued/vetoed sibling
        # EARLIER in the family returns True first
        queued = np.zeros_like(sib_leaf)
        if tr_arr is not None:
            queued |= np.isin(sibs, tr_arr)
        if self.amr.not_to_unrefine:
            queued |= np.isin(
                sibs, self._set_array(self.amr.not_to_unrefine)
            )
        nonleaf = ~sib_leaf
        first_nonleaf = np.where(
            nonleaf.any(axis=1), np.argmax(nonleaf, axis=1), 8
        )
        first_queued = np.where(
            queued.any(axis=1), np.argmax(queued, axis=1), 8
        )
        # (a queued sibling strictly earlier than the first non-leaf one
        # wins the True return)
        ret_false = (first_nonleaf < 8) & ~(first_queued < first_nonleaf)
        out[idx] = ~ret_false
        proceed = (first_nonleaf == 8) & (first_queued == 8)
        idx = idx[proceed]
        if not len(idx):
            return out
        parents = self.mapping.get_parent(ids[idx])
        # family already queued before this storm
        if self.amr.to_unrefine:
            tu = self._set_array(self.amr.to_unrefine)
            queued_parents = np.unique(self.mapping.get_parent(tu))
            fresh = ~np.isin(parents, queued_parents)
            idx, parents = idx[fresh], parents[fresh]
            if not len(idx):
                return out
        # the parent's would-be neighborhood (per-epoch vectorized cache)
        too_fine, has_refining = self._unrefine_parent_info_many(
            parents, tr_arr
        )
        qual = ~too_fine & ~has_refining
        idx, parents = idx[qual], parents[qual]
        if len(idx):
            # first-requested sibling per family wins (np.unique's
            # return_index is the first occurrence in input order)
            _u, first = np.unique(parents, return_index=True)
            self.amr.to_unrefine.update(
                int(c) for c in ids[idx[np.sort(first)]]
            )
        return out

    def dont_unrefine_many(self, cells) -> np.ndarray:
        """Vectorized ``dont_unrefine``; engages when no unrefines are
        pending (nothing to discard), else scalar fallback."""
        ids = np.asarray(cells, dtype=np.uint64).reshape(-1)
        if len(ids) == 0:
            return np.zeros(0, dtype=bool)
        if self.amr.to_unrefine:
            return np.array(
                [self.dont_unrefine(int(c)) for c in ids], dtype=bool
            )
        pos = self.leaves.position(ids)
        exists = pos >= 0
        lvl = np.where(exists, self.mapping.get_refinement_level(ids), 0)
        idx = np.flatnonzero(exists & (lvl > 0))
        if len(idx):
            parents = self.mapping.get_parent(ids[idx])
            if self.amr.not_to_unrefine:
                ntu = self._set_array(self.amr.not_to_unrefine)
                vetoed_parents = np.unique(self.mapping.get_parent(ntu))
                fresh = ~np.isin(parents, vetoed_parents)
                idx, parents = idx[fresh], parents[fresh]
            if len(idx):
                _u, first = np.unique(parents, return_index=True)
                self.amr.not_to_unrefine.update(
                    int(c) for c in ids[idx[np.sort(first)]]
                )
        return exists

    def dont_refine_many(self, cells) -> np.ndarray:
        """Vectorized ``dont_refine`` (always exact: discard + add)."""
        ids = np.asarray(cells, dtype=np.uint64).reshape(-1)
        if len(ids) == 0:
            return np.zeros(0, dtype=bool)
        pos = self.leaves.position(ids)
        exists = pos >= 0
        lvl = self.mapping.get_refinement_level(ids)
        mid = exists & (lvl < self.mapping.max_refinement_level)
        mids = [int(c) for c in ids[mid]]
        self.amr.to_refine.difference_update(mids)
        self.amr.not_to_refine.update(mids)
        return exists

    def _unrefine_parent_info_many(self, parents, tr_arr=None):
        """Vectorized ``_unrefine_parent_info`` over a parent array:
        (too_fine, same-level-neighbor-being-refined) per parent from
        the per-epoch cache.  ``tr_arr``: the caller's to_refine array
        (one conversion per storm)."""
        _, cp, too_fine_all, fcells, fstart = self._build_unrefine_cache()
        i = np.searchsorted(cp, parents)
        ic = np.minimum(i, max(len(cp) - 1, 0))
        found = (i < len(cp)) & (len(cp) > 0)
        if len(cp):
            found &= cp[ic] == parents
        too_fine = np.where(found, too_fine_all[ic] if len(cp) else True,
                            True)
        if tr_arr is None and self.amr.to_refine:
            tr_arr = self._set_array(self.amr.to_refine)
        if tr_arr is not None and len(tr_arr) and len(fcells):
            hit = np.isin(fcells, tr_arr).astype(np.int64)
            csum = np.concatenate(([0], np.cumsum(hit)))
            seg = (csum[fstart[1:]] - csum[fstart[:-1]]) > 0
            has_ref = np.where(found, seg[ic] if len(cp) else False, False)
        else:
            has_ref = np.zeros(len(parents), dtype=bool)
        return too_fine, has_ref

    def refine_completely_at(self, coords) -> bool:
        c = self._cell_at(coords)
        return bool(c) and self.refine_completely(c)

    def unrefine_completely_at(self, coords) -> bool:
        c = self._cell_at(coords)
        return bool(c) and self.unrefine_completely(c)

    def dont_refine_at(self, coords) -> bool:
        c = self._cell_at(coords)
        return bool(c) and self.dont_refine(c)

    def dont_unrefine_at(self, coords) -> bool:
        c = self._cell_at(coords)
        return bool(c) and self.dont_unrefine(c)

    def _cell_at(self, coords) -> int:
        for lvl in range(self.mapping.max_refinement_level, -1, -1):
            c = self.geometry.get_cell(lvl, np.asarray(coords, dtype=np.float64))
            if int(c) and bool(self.leaves.exists(np.uint64(c))):
                return int(c)
        return 0

    def get_existing_cell(self, coords) -> np.ndarray:
        """Existing leaf containing each coordinate (vectorized; 0 for
        outside) — reference ``get_existing_cell`` (``dccrg.hpp:6316``)."""
        coords = np.atleast_2d(np.asarray(coords, dtype=np.float64))
        out = np.zeros(len(coords), dtype=np.uint64)
        unresolved = np.ones(len(coords), dtype=bool)
        for lvl in range(self.mapping.max_refinement_level, -1, -1):
            if not unresolved.any():
                break
            ids = self.geometry.get_cell(lvl, coords[unresolved])
            exists = self.leaves.exists(ids)
            idx = np.flatnonzero(unresolved)
            out[idx[exists]] = ids[exists]
            unresolved[idx[exists]] = False
        return out

    def stop_refining(self, sorted: bool = True, presynced: bool = False) -> np.ndarray:
        """Commit all queued refines/unrefines (veto -> induce -> override
        -> execute, reference ``dccrg.hpp:3461-3485``); returns the new
        cells.  Payload states allocated before this call must be carried
        over with ``remap_state``.  ``presynced`` skips the multi-controller
        queue union for callers that already ran ``sync_adaptation``."""
        self._assert_no_staged_lb()
        self._assert_initialized()
        from .amr.refinement import commit_adaptation
        from .utils.collectives import sync_adaptation

        # multi-controller agreement: every process commits the union of
        # all processes' queued requests (identity under one controller)
        from .obs import metrics

        with self._span_ctx(), metrics.phase("amr.refine"):
            if not presynced:
                sync_adaptation(self.amr)
            old_epoch = self.epoch
            new_cells, removed, delta = commit_adaptation(self)
            self._last_new_cells = new_cells
            self._last_removed_cells = removed
            self._last_adaptation_delta = delta
            if not len(new_cells) and not len(removed):
                # nothing changed (nothing queued, or everything vetoed):
                # the leaf set was left untouched, keep the current epoch
                # and every derived table instead of paying a full rebuild
                self._prev_epoch = None
                return new_cells.copy()
            self._rebuild_incremental(old_epoch)
            self._prev_epoch = _EpochCarry(old_epoch)
            self._harvest_tables(old_epoch)
        return new_cells.copy()

    def get_removed_cells(self) -> np.ndarray:
        """Cells removed by the last ``stop_refining`` (their parents are
        now leaves) — reference ``dccrg.hpp:3488-3520``."""
        return self._last_removed_cells.copy()

    def get_last_adaptation_delta(self):
        """The complete touched set of the last AMR commit
        (``amr.refinement.AdaptationDelta``: every id added to / removed
        from the leaf set, including refined parents and new unrefinement
        parents) — the seed the incremental epoch rebuild patches
        around.  None before the first commit."""
        return getattr(self, "_last_adaptation_delta", None)

    def release_prev_epoch(self) -> None:
        """Drop the retained pre-change carry without remapping any
        payload — for callers with no state to carry across the last
        structural change that want the host memory back immediately.
        ``remap_state`` becomes the identity until the next change."""
        self._prev_epoch = None

    def remap_state(self, state, policy=None):
        """Carry a payload state across the last structural change.

        Surviving cells keep their values.  Per-field ``policy`` entries
        control the rest: ``refine`` — how children get values from their
        refined parent ("inherit" default, or "zero"); ``unrefine`` — how a
        new parent reduces its removed children ("mean" default, "sum", or
        "zero").  This is the array-level form of the reference pattern of
        reading parent/child data after stop_refining
        (tests/advection/adapter.hpp:230-292).

        Memory note: only a slim carry of the old epoch (leaf directory +
        row assignment) is retained across a structural change — the old
        hood tables are freed eagerly at rebuild time.  The carry stays
        so further payloads can be remapped; call ``release_prev_epoch``
        once every payload is across to drop it too.
        """
        if self._prev_epoch is None or self._prev_epoch is self.epoch:
            # no structural change (e.g. a no-move balance_load): identity
            return state
        old, new = self._prev_epoch, self.epoch
        policy = policy or {}
        out = {}
        old_cells = old.leaves.cells
        new_cells = new.leaves.cells

        # classification of new leaves
        surv_pos_new = np.flatnonzero(old.leaves.exists(new_cells))
        fresh_pos_new = np.flatnonzero(~old.leaves.exists(new_cells))
        fresh = new_cells[fresh_pos_new]
        fresh_lvl = self.mapping.get_refinement_level(fresh)
        parents_of_fresh = self.mapping.get_parent(fresh)
        # children created by refinement: their parent was an old leaf
        is_child = old.leaves.exists(parents_of_fresh) & (fresh_lvl > 0)
        # new parents from unrefinement: their children were old leaves
        first_child = self.mapping.get_all_children(fresh)[:, 0]
        is_parent = np.where(
            fresh_lvl < self.mapping.max_refinement_level,
            old.leaves.exists(first_child),
            False,
        ) & ~is_child

        for name, arr in state.items():
            host_old = fetch(arr, dtype=arr.dtype)
            if host_old.ndim < 2 or host_old.shape[:2] != (
                old.n_devices, old.R
            ):
                # not a per-cell [D, R, ...] payload (e.g. a global
                # counter like the particles' overflow scalar) — carry
                # it through unchanged
                out[name] = arr
                continue
            field_shape = host_old.shape[2:]
            host_new = np.zeros((new.n_devices, new.R) + field_shape, host_old.dtype)
            pol = policy.get(name, {})

            def read(ids):
                pos = old.leaves.position(ids)
                dev = old.leaves.owner[pos]
                row = old.row_of[pos]
                return host_old[dev, row]

            def write(ids, values):
                pos = new.leaves.position(ids)
                dev = new.leaves.owner[pos]
                row = new.row_of[pos]
                host_new[dev, row] = values

            surv = new_cells[surv_pos_new]
            write(surv, read(surv))

            children = fresh[is_child]
            if len(children):
                if pol.get("refine", "inherit") == "inherit":
                    write(children, read(parents_of_fresh[is_child]))

            parents = fresh[is_parent]
            if len(parents):
                how = pol.get("unrefine", "mean")
                if how in ("mean", "sum"):
                    fam = self.mapping.get_all_children(parents)  # (M, 8)
                    vals = read(fam.reshape(-1)).reshape((len(parents), 8) + field_shape)
                    red = vals.sum(axis=1)
                    if how == "mean":
                        red = red / 8 if np.issubdtype(red.dtype, np.floating) else red // 8
                    write(parents, red.astype(host_old.dtype))

            out[name] = jax.device_put(
                jnp.asarray(host_new), shard_spec(self.mesh, host_new.ndim)
            )
        return out

    # ------------------------------------------------------------------- IO

    def save_grid_data(self, state, path: str, spec, user_header: bytes = b"",
                       ragged=None, version: int | None = None):
        """Checkpoint grid structure + payloads (reference
        ``save_grid_data``, ``dccrg.hpp:1089-1716``).  ``ragged`` maps a
        variable-size field to its count field: only ``count[i]`` rows are
        written per cell.  ``version=1`` writes the legacy CRC-less
        layout (default: the hardened v2 format)."""
        from .io.checkpoint import CHECKPOINT_VERSION
        from .io.checkpoint import save_grid_data as _save

        with self._span_ctx():
            _save(self, state, path, spec, user_header, ragged=ragged,
                  version=CHECKPOINT_VERSION if version is None else version)

    @staticmethod
    def load_grid_data(path: str, spec, mesh=None, n_devices=None, ragged=None,
                       on_error: str = "raise"):
        """Recreate a saved grid on the current devices; any device count
        works (reference ``load_grid_data``, ``dccrg.hpp:1742-2404``).
        Returns (grid, state, user_header); a torn or corrupt file raises
        :class:`~dccrg_tpu.io.checkpoint.CheckpointError` naming the
        failing section.  ``on_error="salvage"`` instead recovers every
        intact cell and returns ``(grid, state, user_header,
        lost_cells)``."""
        from .io.checkpoint import load_grid_data as _load

        return _load(path, spec, ragged=ragged, mesh=mesh,
                     n_devices=n_devices, on_error=on_error)

    @staticmethod
    def start_loading_grid_data(path: str, spec, mesh=None, n_devices=None,
                                ragged=None, on_error: str = "raise"):
        """Chunked load: returns a loader; call
        ``loader.continue_loading_grid_data(max_cells)`` until it returns
        False, then ``loader.finish_loading_grid_data()`` (reference
        ``dccrg.hpp:1742-2404``)."""
        from .io.checkpoint import start_loading_grid_data as _start

        return _start(path, spec, ragged=ragged, mesh=mesh,
                      n_devices=n_devices, on_error=on_error)

    def save_checkpoint(self, state, directory: str, spec, keep: int = 3,
                        user_header: bytes = b"", ragged=None) -> int:
        """Commit one generation into a crash-safe checkpoint lineage
        (``resilience/manager.py``): fsync'd atomic write, checksummed
        MANIFEST, oldest generations beyond ``keep`` rotated out.
        Returns the committed generation number."""
        from .resilience.manager import CheckpointLineage

        return CheckpointLineage(directory, keep=keep).commit(
            self, state, spec, user_header=user_header, ragged=ragged
        )

    @staticmethod
    def resume_latest(directory: str, spec, mesh=None, n_devices=None,
                      ragged=None, verify: bool = True):
        """Resume from the newest VALID generation in a lineage
        directory, scanning back past torn/corrupt ones and re-verifying
        the restored grid with ``utils.verify.verify_grid``.  Returns
        ``(grid, state, user_header, generation)``; raises
        :class:`~dccrg_tpu.io.checkpoint.CheckpointError` when nothing
        in the lineage is recoverable."""
        from .resilience.manager import CheckpointLineage

        return CheckpointLineage(directory).latest_valid(
            spec, mesh=mesh, n_devices=n_devices, ragged=ragged,
            verify=verify,
        )

    def write_vtk_file(self, path: str, scalars: dict | None = None,
                       binary: bool = True):
        """Dump leaf-cell geometry (+ optional scalars) as legacy VTK
        (reference ``dccrg.hpp:3298-3370``); BINARY encoding by default,
        ``binary=False`` for eyeball-readable ASCII."""
        from .io.vtk import write_vtk_file as _vtk

        _vtk(self, path, scalars, binary=binary)

    # -------------------------------------------------------- introspection

    @property
    def telemetry(self):
        """The process-wide metrics registry (``obs.metrics``) — the
        statistics accessor in dccrg's getter style.  Use
        ``grid.telemetry.report()`` for a raw snapshot, ``grid.report()``
        for the snapshot annotated with this grid's shape."""
        from .obs import metrics

        return metrics

    @property
    def events(self):
        """The process-wide event timeline (``obs.timeline``): the
        individual begin/end spans behind the aggregate phase timers.
        Export with ``obs.export_chrome_trace(path)`` for perfetto."""
        from .obs import timeline

        return timeline

    def report(self) -> dict:
        """Telemetry snapshot (phases, counters, gauges, histograms from
        every instrumented seam) plus this grid's current shape and the
        event-timeline fill state.  The same structure
        ``obs.export_json`` writes to ``telemetry.json``."""
        from .obs import metrics, timeline

        rep = metrics.report()
        rep["events"] = timeline.summary()
        if self.initialized:
            rep["grid"] = {
                "grid_id": int(self.grid_id),
                "n_cells": int(len(self.leaves)),
                "n_devices": int(self.n_devices),
                "rows_per_device": int(self.epoch.R),
                "ghost_cells": int(self.epoch.n_ghost.sum()),
                "neighborhoods": len(self.neighborhoods),
                "max_refinement_level": int(
                    self.mapping.max_refinement_level
                ),
            }
        return rep

    def get_number_of_update_send_cells(self, device: int, hood_id=None) -> int:
        return int(self.epoch.hoods[hood_id].pair_counts[device].sum())

    def get_number_of_update_receive_cells(self, device: int, hood_id=None) -> int:
        return int(self.epoch.hoods[hood_id].pair_counts[:, device].sum())


class _EpochCarry:
    """Slim view of a pre-change epoch: exactly what ``remap_state``
    needs to carry payloads across a structural change (the old leaf
    directory, row assignment and row budget).  Retaining this instead
    of the full ``Epoch`` frees the old hood tables — the ``[D, R,
    Kmax]`` gather tables and send/recv schedules, i.e. the bulk of a
    second epoch's host memory — eagerly at rebuild time instead of
    holding them until the next structural change."""

    __slots__ = ("leaves", "row_of", "n_devices", "R")

    def __init__(self, epoch):
        self.leaves = epoch.leaves
        self.row_of = epoch.row_of
        self.n_devices = epoch.n_devices
        self.R = epoch.R


class _SubGridView:
    """Minimal grid-shaped view over a subset of leaves, for hierarchical
    partitioning."""

    def __init__(self, grid, idx):
        from .core.neighbors import LeafSet

        self.mapping = grid.mapping
        self.geometry = grid.geometry
        self.leaves = LeafSet(
            cells=grid.leaves.cells[idx], owner=grid.leaves.owner[idx]
        )


def _face_direction(off, own_len: int, nbr_len: int) -> int:
    """Classify a neighbor-list offset as a face direction (0 = not a face
    neighbor), following the advection workload's offset logic
    (reference tests/advection/solve.hpp:71-123)."""
    ox, oy, oz = (int(v) for v in off)
    span = nbr_len
    for axis, o in ((1, ox), (2, oy), (3, oz)):
        others = [v for a, v in ((1, ox), (2, oy), (3, oz)) if a != axis]
        # face contact on the negative side: neighbor ends where cell begins
        if o == -nbr_len and all(-nbr_len < v < own_len for v in others):
            return -axis
        if o == own_len and all(-nbr_len < v < own_len for v in others):
            return axis
    return 0
