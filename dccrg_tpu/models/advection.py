"""3-D upwind finite-volume advection — the framework's north-star workload
(reference ``tests/advection``: cell layout ``cell.hpp:36-44``, flux solver
``solve.hpp:43-260``, initial condition ``initialize.hpp:36-80``, rotating
velocity field ``solve.hpp:336-346``).

TPU-native formulation: instead of the reference's per-cell loop that
scatters flux into both cells of each face pair (skipping local negative
directions), every cell accumulates its *own* flux from all of its
face-neighbor entries in fixed slot order.  That makes the kernel a pure
gather + masked reduction — deterministic (fixed left-to-right flux
association via ``ordered_sum``; halo copies are bit-exact, and results
across device counts agree to the last ulp, where the residual is XLA
instruction selection varying with local array shapes, not data flow) and
scatter-free — at the cost of computing each face's flux twice,
which on TPU is free relative to the HBM traffic.

Face classification (direction, shared area, volumes) depends only on grid
structure, so it is precomputed host-side per epoch and shipped as device
tables; the jitted step touches only density (1 f64 per ghost cell per step,
matching the reference's density-only ``get_mpi_datatype``).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import put_table, shard_spec
from ..parallel.stencil import StencilTables, gather_neighbors, ordered_sum
from ..utils.collectives import fetch
from ..utils.fallback import fallback_call

__all__ = ["Advection"]


def _calibrated_edge(key: str, default: float) -> float:
    """A flat-vs-boxed dispatch edge constant: prefer the boxed
    per-level passes when ``flat_n_vox > edge * boxed_vol``.  Measured
    on chip and written by ``tools/recalibrate.py --write``.  A missing,
    malformed, or out-of-range file falls back to the default — a
    calibration artifact must never break or silently pin the
    dispatch."""
    import json
    import math
    import pathlib

    path = (pathlib.Path(__file__).resolve().parents[2]
            / "tools" / "dispatch_calibration.json")
    try:
        edge = float(json.loads(path.read_text())[key])
    except (OSError, KeyError, ValueError, TypeError):
        return default
    if not math.isfinite(edge) or not 0.5 <= edge <= 100.0:
        return default
    return edge


def _flat_boxed_edge() -> float:
    """2-level Pallas-kernel edge; default = the r2-measured ~2x flat
    per-voxel advantage."""
    return _calibrated_edge("flat_boxed_edge", 2.0)


def build_face_tables(grid, hood_id, tables, dtype, hood_arrays=None):
    """Classify each neighbor entry as a face neighbor with a signed
    direction, reproducing the offset logic of ``solve.hpp:71-123``
    (overlap in exactly 2 dims + contact in 1), plus the physical
    factors every finite-volume workload prices faces with.  Shared by
    Advection and the AMR Vlasov path.  Returns ``(host, dev)``: numpy
    tables {face_dir, min_area, cell_axis_len, nbr_axis_len,
    inv_volume} and the device dict (axis_idx included) for jitted
    steps.

    ``hood_arrays`` overrides the neighbor tables the classification
    reads: an ``(nbr_offset, nbr_len, nbr_rows, nbr_valid)`` tuple, e.g.
    a wide-halo plan's device-extended tables (ISSUE 14) whose ghost
    rows also carry gather entries.  The geometry side
    (``tables.length``, ``epoch.cell_len``) already covers ghost rows,
    so the same pricing applies; owner-local rows stay bitwise equal to
    the default-hood result."""
    from ..core.neighbors import face_directions

    epoch = grid.epoch
    if hood_arrays is None:
        hood = epoch.hoods[hood_id]
        hood_arrays = (hood.nbr_offset, hood.nbr_len, hood.nbr_rows,
                       hood.nbr_valid)
    h_off, h_nlen, nb, valid = hood_arrays
    off = np.asarray(h_off).astype(np.int64)        # [D, R, K, 3]
    nlen = np.asarray(h_nlen).astype(np.int64)      # [D, R, K]
    clen = epoch.cell_len.astype(np.int64)[..., None]  # [D, R, 1]
    valid = np.asarray(valid)

    direction = np.where(
        valid, face_directions(off, clen, nlen), 0
    ).astype(np.int8)                                # [D, R, K] signed axis or 0

    # physical areas/volumes from geometry tables
    length = np.asarray(tables.length)               # [D, R, 3]
    vol = length.prod(axis=-1)                       # [D, R]
    # gather neighbor physical lengths host-side
    D, R, K = np.asarray(nb).shape
    nlen_phys = length[np.arange(D)[:, None, None], nb]  # [D, R, K, 3]

    axis_idx = np.abs(direction).astype(np.int64) - 1    # [D, R, K]
    ai = np.maximum(axis_idx, 0)
    other = np.stack([(ai + 1) % 3, (ai + 2) % 3], axis=-1)
    cell_area = np.take_along_axis(
        np.broadcast_to(length[:, :, None], nlen_phys.shape), other, axis=-1
    ).prod(axis=-1)
    nbr_area = np.take_along_axis(nlen_phys, other, axis=-1).prod(axis=-1)
    min_area = np.minimum(cell_area, nbr_area)
    is_face = direction != 0
    host = {
        "face_dir": direction,
        "min_area": np.where(is_face, min_area, 0.0),
        # axis lengths for face-velocity interpolation
        "cell_axis_len": np.take_along_axis(
            np.broadcast_to(length[:, :, None], nlen_phys.shape),
            ai[..., None], axis=-1,
        )[..., 0],
        "nbr_axis_len": np.take_along_axis(
            nlen_phys, ai[..., None], axis=-1
        )[..., 0],
        "inv_volume": np.where(vol > 0, 1.0 / vol, 0.0),
    }
    mesh = grid.mesh
    put = lambda a, dt: put_table(a, mesh, dt)
    dev = {
        "face_dir": put(host["face_dir"], jnp.int8),
        "min_area": put(host["min_area"], dtype),
        "cell_axis_len": put(host["cell_axis_len"], dtype),
        "nbr_axis_len": put(host["nbr_axis_len"], dtype),
        "inv_volume": put(host["inv_volume"], dtype),
        "axis_idx": put(ai, jnp.int8),
    }
    return host, dev


def build_split_tables(grid, hood_id, host_face, dtype, extra=None):
    """Compacted inner/outer row sets with the gather + face tables
    restricted to them — the runtime-argument pack of a fused
    split-phase step (shared by Advection and Vlasov).

    ``host_face`` is the host dict :func:`build_face_tables` returned;
    ``extra`` maps names to additional ``[D, R]`` host tables restricted
    per side and shipped at ``dtype`` (Vlasov's open-boundary face
    areas).  Returns ``(inner, outer, local)`` device pytrees; padding
    rows point at the scratch row, whose face entries are all masked
    (``face_dir == 0``), so padded lanes contribute exactly nothing."""
    from ..parallel.shapes import bucket_rows
    from ..parallel.stencil import compact_rows

    epoch = grid.epoch
    hood = epoch.hoods[hood_id]
    scratch = epoch.R - 1
    D = epoch.n_devices
    ar = np.arange(D)[:, None]
    mesh = grid.mesh
    put = lambda a, dt=None: put_table(a, mesh, dt)
    # compacted widths ride the bucket ladder with grid-persistent
    # hysteresis hints (the ring-size discipline of parallel/shapes.py):
    # inner/outer counts wiggling with churn must not retrace the fused
    # split kernels — pad slots are scratch rows whose face entries are
    # all masked, so they contribute exactly nothing
    hints = getattr(grid, "_ring_hints", {})
    sides = []
    for side, mask in (("inner", hood.inner_mask),
                       ("outer", hood.outer_mask)):
        counts = mask.sum(axis=1)
        natural = max(int(counts.max()) if D else 0, 1)
        hint_key = (hood_id, f"split.{side}", 0)
        W = bucket_rows(natural, hints.get(hint_key))
        hints[hint_key] = W
        rows = compact_rows(mask, scratch, width=W)
        fd = host_face["face_dir"][ar, rows]
        sub = {
            "rows": put(rows),
            "nbr_rows": put(hood.nbr_rows[ar, rows]),
            "face_dir": put(fd, jnp.int8),
            "axis_idx": put(
                np.maximum(np.abs(fd.astype(np.int64)) - 1, 0), jnp.int8
            ),
            "min_area": put(host_face["min_area"][ar, rows], dtype),
            "cell_axis_len": put(
                host_face["cell_axis_len"][ar, rows], dtype
            ),
            "nbr_axis_len": put(host_face["nbr_axis_len"][ar, rows], dtype),
            "inv_volume": put(host_face["inv_volume"][ar, rows], dtype),
        }
        for name, arr in (extra or {}).items():
            sub[name] = put(arr[ar, rows], dtype)
        sides.append(sub)
    return sides[0], sides[1], put(epoch.local_mask)


def _table_specs(tabs):
    """shard_map in_specs pytree for a split-table pack: every leaf is a
    ``[D, ...]`` array sharded on the device axis."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import SHARD_AXIS

    return jax.tree_util.tree_map(
        lambda x: P(SHARD_AXIS, *([None] * (x.ndim - 1))), tabs
    )


def _ml_boxed_edge(kind: str) -> float:
    """Multi-level (3+ level) whole-run edge, per FORM: the
    VMEM-resident Pallas kernel and the streaming XLA pyramid have
    different per-voxel rates, so each calibrates from a battery run
    that measured ITS kind (tools/recalibrate.py names the key after
    refined3_ml's recorded path).  Defaults until measured: 2.0 for the
    kernel (the 2-level kernel's measured class of advantage), 1.5 for
    the XLA form (streams like the boxed passes, modest slack for their
    per-level pass/concat overhead)."""
    if kind == "ml_pallas":
        return _calibrated_edge("ml_pallas_boxed_edge", 2.0)
    return _calibrated_edge("ml_boxed_edge", 1.5)


class Advection:
    #: the reference's 9-double cell (density, velocity, flux, max_diff;
    #: lengths live in the geometry tables instead of per-cell storage)
    SPEC = {
        "density": ((), np.float64),
        "vx": ((), np.float64),
        "vy": ((), np.float64),
        "vz": ((), np.float64),
        "flux": ((), np.float64),
        "max_diff": ((), np.float64),
    }

    def __init__(self, grid, hood_id=None, dtype=np.float64, allow_dense=True,
                 use_pallas=True, allow_boxed=True, overlap=False):
        self.grid = grid
        self.hood_id = hood_id
        self.dtype = dtype
        self.use_pallas = use_pallas
        #: split-phase stepping (ISSUE 7): ``step``/``run`` use the fused
        #: start → interior → finish → boundary body on the general
        #: gather path, bit-identical to the blocking step.  Like GoL's
        #: ``overlap=True``, this pins the general path (the split form
        #: exists to overlap the halo seam the fast paths do not have).
        self.overlap = bool(overlap)
        self.spec = {k: (s, dtype) for k, (s, _) in self.SPEC.items()}
        self.dense = (grid.epoch.dense if allow_dense and not overlap
                      else None)
        self.boxed = None
        if self.dense is not None:
            self._init_dense()
            return
        self.tables = StencilTables(grid, hood_id, with_geometry=True)
        self._exchange = grid.halo(hood_id)
        # halo schedule tables ride into the cached kernels as runtime
        # arguments (parallel/exec_cache.py): an epoch rebuild with the
        # same shape signature reuses every compiled step
        self._rings = (tuple(self._exchange.ring_send)
                       + tuple(self._exchange.ring_recv))
        self._build_face_tables()
        self._step = self._build_step()
        self._max_dt = self._build_max_dt()
        self._max_diff = self._build_max_diff()
        if self.overlap:
            self._step = self._build_split_step()
        if allow_boxed and not self.overlap:
            from ..parallel.boxed import build_boxed

            self.boxed = build_boxed(grid, hood_id)
            if self.boxed is not None:
                self._boxed_run = self._build_boxed_run(self.boxed)
            # the flat two-level scheme qualifies independently of the
            # boxed layout (e.g. wrap-adjacent refinement is gated out of
            # slab-mode boxed but handled exactly by the flat rolls)
            self._flat_run = self._build_flat_run()
            # cost-based choice when both fast paths qualify: prefer
            # boxed only when the flat form's voxel inflation exceeds
            # its per-voxel rate advantage over the boxed passes.  Each
            # compiled form reads its own edge constant from
            # tools/dispatch_calibration.json (written by
            # ``tools/recalibrate.py --write`` from the on-chip
            # battery's pinned measurements: flat_boxed_edge for the
            # 2-level kernel, ml_pallas_boxed_edge / ml_boxed_edge for
            # the multi-level forms), with documented defaults until a
            # battery run lands.  Interpret mode (tests) and the
            # 2-level sharded XLA form keep the flat preference so the
            # flat numerics stay exercised
            if (
                self._flat_kind in ("pallas", "ml", "ml_pallas")
                and self._flat_run is not None
                and self.boxed is not None
            ):
                boxed_vol = sum(
                    int(np.prod(b.shape)) for b in self.boxed.boxes.values()
                )
                edge = (_flat_boxed_edge() if self._flat_kind == "pallas"
                        else _ml_boxed_edge(self._flat_kind))
                self._prefer_boxed = self._flat_n_vox > edge * boxed_vol

    # ------------------------------------------------------ static tables

    def _build_face_tables(self):
        host, dev = build_face_tables(
            self.grid, self.hood_id, self.tables, self.dtype
        )
        self.face_dir = host["face_dir"]
        self.min_area = host["min_area"]
        self.cell_axis_len = host["cell_axis_len"]
        self.nbr_axis_len = host["nbr_axis_len"]
        self.inv_volume = host["inv_volume"]
        self._dev = dev

    # -------------------------------------------------------------- kernels

    def _kernel_key(self, name: str) -> tuple:
        return (name, self._exchange.structure_key,
                str(np.dtype(self.dtype)))

    def _build_step(self):
        from ..parallel.exec_cache import traced_jit

        ex_body = self._exchange.raw_body

        def build():
            def step(rings, t, dev, state, dt):
                # ghost refresh: density only, like the reference's
                # default get_mpi_datatype (cell.hpp:46-55)
                state = {
                    **state,
                    **ex_body(*rings, {"density": state["density"]}),
                }

                rho = state["density"]
                nbr = t["nbr_rows"]
                rho_n = gather_neighbors(rho, nbr)           # [D, R, K]
                vx_n = gather_neighbors(state["vx"], nbr)
                vy_n = gather_neighbors(state["vy"], nbr)
                vz_n = gather_neighbors(state["vz"], nbr)

                sgn = jnp.sign(dev["face_dir"]).astype(rho.dtype)
                ai = dev["axis_idx"]
                v_cell = jnp.where(
                    ai == 0, state["vx"][..., None],
                    jnp.where(ai == 1, state["vy"][..., None],
                              state["vz"][..., None]),
                )
                v_nbr = jnp.where(
                    ai == 0, vx_n, jnp.where(ai == 1, vy_n, vz_n)
                )
                cl, nl = dev["cell_axis_len"], dev["nbr_axis_len"]
                # velocity interpolated to the shared face
                # (solve.hpp:168-175)
                v_face = (cl * v_nbr + nl * v_cell) / (cl + nl)

                upwind_pos = jnp.where(v_face >= 0, rho[..., None], rho_n)
                upwind_neg = jnp.where(v_face >= 0, rho_n, rho[..., None])
                upwind = jnp.where(sgn > 0, upwind_pos, upwind_neg)
                face_flux = upwind * dt * v_face * dev["min_area"]
                # +dir face: outflow subtracts; -dir face: adds
                # (solve.hpp:227-233)
                contrib = jnp.where(
                    dev["face_dir"] != 0, -sgn * face_flux, 0.0
                )
                flux = ordered_sum(contrib, axis=-1) * dev["inv_volume"]

                local = t["local_mask"]
                new_rho = jnp.where(local, rho + flux, rho)
                return {**state, "density": new_rho,
                        "flux": jnp.zeros_like(flux)}

            return traced_jit("advection.step", step)

        fn = self.grid.exec_cache.get(self._kernel_key("advection.step"),
                                      build)
        self._step_fn = fn
        rings, t, dev = self._rings, self.tables.tree(), self._dev
        return lambda state, dt: fn(rings, t, dev, state, dt)

    def _build_split_step(self):
        """Fused split-phase step (ISSUE 7; the reference's
        ``dccrg.hpp:5010-5367`` overlap pattern as ONE compiled
        program): dispatch the ghost payloads, compute the flux of the
        compacted inner rows with no data dependence on the transfer,
        merge the ghosts (the wait), then the outer rows.  The XLA
        scheduler — or the Pallas DMA engine when the halo backend is
        ``pallas`` — overlaps the transfer with interior compute without
        relying on host async dispatch.

        Bit-identical to the blocking step: inner rows gather only local
        rows, which the exchange never writes, and invalid-slot gathers
        (scratch-row padding the exchange DOES write) are masked by
        ``face_dir == 0`` in both forms before the ordered reduction."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.exec_cache import traced_jit
        from ..parallel.halo import HaloExchange
        from ..parallel.mesh import SHARD_AXIS
        from ..utils.compat import shard_map

        ex = self._exchange
        host_face = {
            "face_dir": self.face_dir,
            "min_area": self.min_area,
            "cell_axis_len": self.cell_axis_len,
            "nbr_axis_len": self.nbr_axis_len,
            "inv_volume": self.inv_volume,
        }
        inner, outer, local = build_split_tables(
            self.grid, self.hood_id, host_face, self.dtype
        )
        ring_start = ex.make_ring_start()
        mesh = self.grid.mesh
        ks = tuple(ex.ring_ks)

        def build():
            nk = len(ks)
            data_spec = P(SHARD_AXIS)
            idx_spec = P(SHARD_AXIS, None)

            def side_update(rho, vx, vy, vz, t, dt):
                # the blocking step's flux math verbatim, restricted to
                # one compacted row set (same ops, same slot order —
                # that is the bit-identity argument)
                rows = t["rows"]
                rho_c = rho[rows]                            # [W]
                nbr = t["nbr_rows"]
                rho_n = rho[nbr]                             # [W, K]
                vx_n, vy_n, vz_n = vx[nbr], vy[nbr], vz[nbr]
                sgn = jnp.sign(t["face_dir"]).astype(rho.dtype)
                ai = t["axis_idx"]
                v_cell = jnp.where(
                    ai == 0, vx[rows][..., None],
                    jnp.where(ai == 1, vy[rows][..., None],
                              vz[rows][..., None]),
                )
                v_nbr = jnp.where(
                    ai == 0, vx_n, jnp.where(ai == 1, vy_n, vz_n)
                )
                cl, nl = t["cell_axis_len"], t["nbr_axis_len"]
                v_face = (cl * v_nbr + nl * v_cell) / (cl + nl)
                upwind_pos = jnp.where(v_face >= 0, rho_c[..., None], rho_n)
                upwind_neg = jnp.where(v_face >= 0, rho_n, rho_c[..., None])
                upwind = jnp.where(sgn > 0, upwind_pos, upwind_neg)
                face_flux = upwind * dt * v_face * t["min_area"]
                contrib = jnp.where(
                    t["face_dir"] != 0, -sgn * face_flux, 0.0
                )
                return rho_c + ordered_sum(contrib, axis=-1) * t["inv_volume"]

            def body(*args):
                sends = [a[0] for a in args[:nk]]
                recvs = [a[0] for a in args[nk:2 * nk]]
                ti, to, local, rho, vx, vy, vz, dt = args[2 * nk:]
                sub = lambda t: {k: v[0] for k, v in t.items()}
                ti, to = sub(ti), sub(to)
                a = rho[0]
                vx, vy, vz = vx[0], vy[0], vz[0]
                # --- start: ghost payloads in flight (depend on `a`)
                payloads = ring_start(a, sends)
                # --- interior: no remote neighbors, no dep on payloads
                new_i = side_update(a, vx, vy, vz, ti, dt)
                # --- wait: merging the payloads IS the synchronization
                a2 = HaloExchange.ring_finish(a, recvs, payloads)
                # --- boundary: needs the fresh ghosts
                new_o = side_update(a2, vx, vy, vz, to, dt)
                out = a2.at[ti["rows"]].set(new_i).at[to["rows"]].set(new_o)
                out = jnp.where(local[0], out, a2)       # clean scratch
                return out[None]

            fn = shard_map(
                body,
                mesh=mesh,
                in_specs=(idx_spec,) * (2 * nk)
                + (_table_specs(inner), _table_specs(outer), idx_spec)
                + (data_spec,) * 4 + (P(),),
                out_specs=data_spec,
                check_vma=False,
            )

            def step(rings, ti, to, local, state, dt):
                new_rho = fn(
                    *rings, ti, to, local, state["density"], state["vx"],
                    state["vy"], state["vz"], dt,
                )
                return {**state, "density": new_rho,
                        "flux": jnp.zeros_like(new_rho)}

            return traced_jit("advection.split_step", step)

        fn = self.grid.exec_cache.get(
            self._kernel_key("advection.split_step"), build
        )
        self._split_fn = fn
        self._split_args = (self._rings, inner, outer, local)
        args = self._split_args
        return lambda state, dt: fn(*args, state, dt)

    def _build_max_dt(self):
        from ..parallel.exec_cache import traced_jit

        def build():
            def max_dt(t, state):
                # CFL: min over local cells of length/|v| per dim, global
                # min (solve.hpp:284-330)
                length = t["length"]
                steps = jnp.stack(
                    [
                        length[..., 0] / jnp.abs(state["vx"]),
                        length[..., 1] / jnp.abs(state["vy"]),
                        length[..., 2] / jnp.abs(state["vz"]),
                    ],
                    axis=-1,
                )
                ok = (jnp.isfinite(steps) & (steps > 0)
                      & t["local_mask"][..., None])
                steps = jnp.where(ok, steps, jnp.inf)
                return jnp.min(steps)

            return traced_jit("advection.max_dt", max_dt)

        fn = self.grid.exec_cache.get(
            ("advection.max_dt", str(np.dtype(self.dtype))), build
        )
        t = self.tables.tree()
        return lambda state: fn(t, state)

    def _build_max_diff(self):
        from ..parallel.exec_cache import traced_jit

        ex_body = self._exchange.raw_body

        def build():
            def max_diff(rings, t, dev, state, diff_threshold):
                """Max relative density difference to face neighbors
                (adapter.hpp:71-110) — the AMR refinement indicator."""
                state = {
                    **state,
                    **ex_body(*rings, {"density": state["density"]}),
                }
                rho = state["density"]
                rho_n = gather_neighbors(rho, t["nbr_rows"])
                diff = jnp.abs(rho[..., None] - rho_n) / (
                    jnp.minimum(rho[..., None], rho_n) + diff_threshold
                )
                diff = jnp.where(dev["face_dir"] != 0, diff, 0.0)
                md = diff.max(axis=-1)
                return {**state,
                        "max_diff": jnp.where(t["local_mask"], md, 0.0)}

            return traced_jit("advection.max_diff", max_diff)

        fn = self.grid.exec_cache.get(
            self._kernel_key("advection.max_diff"), build
        )
        rings, t, dev = self._rings, self.tables.tree(), self._dev
        return lambda state, thr: fn(rings, t, dev, state, thr)

    # ------------------------------------------------------ boxed AMR path

    def _build_flat_run(self):
        """Whole-run fused kernel for two-level AMR on the flat inflated
        grid (ops/flat_amr.py): the entire run loop in VMEM, one launch.
        None when the grid/device/dtype does not qualify; the boxed path
        remains the general fallback (and the step()/indicator path)."""
        from ..ops.dense_advection import have_pallas, pallas_available
        from ..ops.flat_amr import (
            build_flat_amr_sharded,
            build_flat_amr_tables,
            build_flat_ml_tables,
            compute_flat_weights,
            flat_amr_fits,
            make_flat_amr_run,
            make_flat_amr_run_sharded,
            make_flat_ml_run,
            pad_lane_extent,
        )

        # use_pallas doubles as the fast-path opt-out: False always means
        # the reference boxed numerics
        self._flat_kind = None
        if not self.use_pallas:
            return None

        # 3+ leaf levels: the multi-level flat whole-run forms — the
        # VMEM-resident Pallas kernel when a single device, f32, and the
        # budget allow, else the XLA pyramid form (any device count) —
        # VERDICT-r4's extension of the fast path past levels {0, 1}
        tml = build_flat_ml_tables(self.grid)
        if tml is not None:
            from ..ops.flat_amr import flat_ml_kernel_fits

            self._flat_n_vox = int(tml["n_vox"])
            interpret = self.use_pallas == "interpret"
            if (
                tml["n_devices"] == 1
                and np.dtype(self.dtype) == np.float32
                and have_pallas()
                and (interpret or pallas_available(self.dtype))
                and flat_ml_kernel_fits(self._flat_n_vox, tml["vl"])
            ):
                self._flat_kind = ("ml_pallas_interpret" if interpret
                                   else "ml_pallas")
                return self._build_ml_pallas_run(tml, interpret)
            jdt = (
                jnp.float32
                if np.dtype(self.dtype) == np.float32
                else jnp.float64
            )
            self._flat_kind = "ml"
            return make_flat_ml_run(self.grid, tml, dtype=jdt)

        # multi-device: z-slab-sharded XLA form (no Pallas requirement)
        ts = build_flat_amr_sharded(self.grid)
        if ts is not None:
            jdt = (
                jnp.float32
                if np.dtype(self.dtype) == np.float32
                else jnp.float64
            )
            self._flat_n_vox = int(np.prod(ts["shape"])) * ts["n_devices"]
            self._flat_kind = "sharded"
            return make_flat_amr_run_sharded(self.grid, ts, dtype=jdt)

        interpret = self.use_pallas == "interpret"
        if not have_pallas():
            return None
        if np.dtype(self.dtype) != np.float32:
            return None
        if not (interpret or pallas_available(self.dtype)):
            return None
        t = build_flat_amr_tables(self.grid)
        if t is None:
            return None
        nz1, ny1, nx1 = t["shape"]
        self._flat_n_vox = nz1 * ny1 * nx1
        self._flat_kind = "pallas_interpret" if interpret else "pallas"
        # lane-align the x extent when the pad fits VMEM: Mosaic pads
        # registers to 128 lanes regardless, so the explicit pad costs no
        # extra compute and turns the 12 per-step x rolls lane-aligned
        nxp = pad_lane_extent(nx1)
        if nxp != nx1 and not flat_amr_fits(nz1 * ny1 * nxp):
            nxp = nx1
        self._flat_nx_pad = nxp if nxp != nx1 else None
        kernel = make_flat_amr_run(nz1, ny1, nx1, nx_pad=self._flat_nx_pad,
                                   interpret=interpret)
        leaf = t["leaf_fine"]
        # runtime-argument tables (not closed over): the jitted body is
        # content-independent, so regridding rebuilds only this pytree
        tabs = {
            "rows": jnp.asarray(t["rows"]),
            "updf": jnp.asarray(
                leaf.astype(np.float64) / t["vol_f"], jnp.float32
            ),
            "updc": jnp.asarray(
                (~leaf).astype(np.float64) / t["vol_c"], jnp.float32
            ),
            "wb_rows": jnp.asarray(t["wb_rows"]),
            "wb_valid": jnp.asarray(t["wb_valid"]),
        }

        @jax.jit
        def run_fn(tabs, state, steps, dt):
            def field(name):
                return state[name][0][tabs["rows"]].reshape(nz1, ny1, nx1)

            V = field("density")
            w = compute_flat_weights(
                t, field("vx"), field("vy"), field("vz")
            )
            (wpx, wnx), (wpy, wny), (wpz, wnz) = w
            out = kernel(
                V, wpx, wnx, wpy, wny, wpz, wnz,
                tabs["updf"], tabs["updc"],
                jnp.asarray(dt, jnp.float32), steps,
            )
            rho = jnp.where(
                tabs["wb_valid"], out.reshape(-1)[tabs["wb_rows"]],
                state["density"][0],
            )
            return {
                **state,
                "density": rho[None].astype(state["density"].dtype),
                "flux": jnp.zeros_like(state["flux"]),
            }

        return lambda state, steps, dt: run_fn(tabs, state, steps, dt)

    def _build_ml_pallas_run(self, t, interpret):
        """VMEM-resident whole-run for a 3+-level grid on one device:
        voxelize, compute the per-face weights once, run every step
        inside one Pallas launch (ops/flat_amr.make_flat_ml_run_pallas),
        write back leaf rows."""
        from ..ops.flat_amr import (
            compute_flat_ml_weights,
            make_flat_ml_run_pallas,
        )

        nzl, nyv, nxv = t["shape"]
        kernel = make_flat_ml_run_pallas(
            nzl, nyv, nxv, t["vl"], t["cap_active"], interpret=interpret
        )
        rows = jnp.asarray(t["rows"][0])
        updf = jnp.asarray(t["updf"][0], jnp.float32)
        pool = jnp.asarray(t["pool"][0], jnp.float32)
        caps = [jnp.asarray(c[0], jnp.float32) for c in t["cap_origin"]]
        wb_rows = jnp.asarray(t["wb_rows"][0])
        wb_valid = jnp.asarray(t["wb_valid"][0])

        @jax.jit
        def run_fn(state, steps, dt):
            def field(name):
                return (state[name][0][rows]
                        .reshape(nzl, nyv, nxv).astype(jnp.float32))

            V = field("density")
            w = compute_flat_ml_weights(
                t, field("vx"), field("vy"), field("vz")
            )
            (wpx, wnx), (wpy, wny), (wpz, wnz) = w
            out = kernel(
                V, wpx, wnx, wpy, wny, wpz, wnz, updf, pool, caps,
                jnp.asarray(dt, jnp.float32), steps,
            )
            rho = jnp.where(
                wb_valid, out.reshape(-1)[wb_rows], state["density"][0]
            )
            return {
                **state,
                "density": rho[None].astype(state["density"].dtype),
                "flux": jnp.zeros_like(state["flux"]),
            }

        return run_fn

    def _build_boxed_run(self, layout):
        """Multi-step run over the boxed per-level AMR layout — one unified
        dense pass per level per step, z-slab sharded over the device mesh
        with circular ppermute plane rings.  See
        ``models/boxed_advection.py`` for the full scheme and the
        multi-device correctness argument."""
        from .boxed_advection import build_boxed_run

        return build_boxed_run(self, layout)

    # ------------------------------------------------------ dense fast path

    def _init_dense(self):
        """Uniform-grid specialization (parallel/dense.py): payloads as
        dense [D, nzl, ny, nx] z-slab blocks, the halo as two ppermute plane
        transfers, and every face flux as shifted slices that XLA fuses into
        one HBM pass — the layout the reference's per-cell object model
        cannot express but the one a TPU needs.

        Every compiled artifact is a pure function of (mesh, dims,
        periodicity, cell size, dtype, pallas mode), so the whole kernel
        bundle is cached under that key — an adapt cycle that returns to
        the same uniform shape redispatches the existing executables."""
        from ..parallel.exec_cache import mesh_key

        info = self.dense
        l0 = self.grid.geometry.get_level_0_cell_length()
        self._dx = l0.astype(np.float64)
        self._vol = float(l0.prod())
        pallas_mode = (self.use_pallas if isinstance(self.use_pallas, str)
                       else bool(self.use_pallas))
        key = (
            "advection.dense", mesh_key(self.grid.mesh), info.n_devices,
            info.nz_local, info.ny, info.nx,
            tuple(bool(p) for p in info.periodic),
            str(np.dtype(self.dtype)), pallas_mode,
            tuple(np.asarray(l0, np.float64).tolist()),
        )
        self._dense_key = key
        bundle = self.grid.exec_cache.get(key, self._build_dense_bundle)
        self._step = bundle["step"]
        self._fused_run = bundle["fused_run"]
        self._dense_run = bundle["dense_run"]
        self._max_dt = bundle["max_dt"]
        self._max_diff = bundle["max_diff"]
        self.dense_kind = bundle["dense_kind"]

    def _build_dense_bundle(self) -> dict:
        from ..utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel.dense import HaloExtend
        from ..parallel.exec_cache import traced_jit
        from ..parallel.mesh import SHARD_AXIS, shard_spec

        info = self.dense
        grid = self.grid
        dtype = self.dtype
        D, nzl, ny, nx = info.n_devices, info.nz_local, info.ny, info.nx
        l0 = grid.geometry.get_level_0_cell_length()
        area = np.array([l0[1] * l0[2], l0[0] * l0[2], l0[0] * l0[1]])
        vol = float(l0.prod())
        px, py, pz = info.periodic
        extend = HaloExtend(info)
        mesh = grid.mesh
        data_spec = P(SHARD_AXIS)

        # Face validity masks for non-periodic boundaries.  "Face i" along a
        # dimension sits between cell i and cell (i+1) mod n; the wrapping
        # face is invalid unless that dimension is periodic (a neighborhood
        # slot outside the grid has no neighbor, hence no flux).
        mask_x = np.ones(nx)
        mask_y = np.ones(ny)
        if not px:
            mask_x[-1] = 0.0
        if not py:
            mask_y[-1] = 0.0
        # z-face validity per (device, local plane): face above plane g is
        # invalid for the global top plane unless periodic
        zface_up = np.ones((D, nzl))
        if not pz:
            zface_up[-1, -1] = 0.0
        # validity of the face *below* plane g = validity of the face above
        # plane g-1
        zface_dn = np.roll(zface_up.reshape(-1), 1).reshape(D, nzl)
        put = lambda a: put_table(a, mesh, dtype)
        zf_up_dev, zf_dn_dev = put(zface_up), put(zface_dn)
        mx = jnp.asarray(mask_x, dtype)[None, None, :]
        my = jnp.asarray(mask_y, dtype)[None, :, None]
        area = area.astype(dtype)

        def face_flux(rho_c, rho_n, v_c, v_n, area_d, dt):
            # uniform cells: the reference's length-weighted face velocity
            # (solve.hpp:168-175) reduces to the plain average
            v_face = (v_c + v_n) * dtype(0.5)
            up = jnp.where(v_face >= 0, rho_c, rho_n)
            return up * (dt * v_face * area_d)

        # Optional fused Pallas kernel (TPU + f32): same update, one VMEM
        # pass per z-slab instead of XLA-materialized rolls
        from ..ops.dense_advection import (
            flux_update_fits,
            fused_run_fits,
            make_flux_update,
            make_flux_update_blocked_direct,
            make_fused_run,
            pallas_available,
            pick_step_block,
        )

        pallas_update = None
        blocked_update = None
        step_block = 0
        #: which per-step dense kernel engaged — ("blocked_direct", B) /
        #: ("plane",) / ("xla",) — so the bench's HBM-traffic model can
        #: count the bytes the engaged path actually moves
        dense_kind = ("xla",)
        use_pallas = getattr(self, "use_pallas", True)
        # use_pallas="interpret" forces the kernels through the Pallas
        # interpreter so CI (CPU) exercises the full integration path
        interpret = use_pallas == "interpret"
        if use_pallas and (interpret or pallas_available(dtype)):
            step_block = pick_step_block(nzl, ny, nx)
            if step_block >= 2:
                blocked_update = make_flux_update_blocked_direct(
                    nzl, ny, nx, step_block, area, 1.0 / vol,
                    interpret=interpret,
                )
                dense_kind = ("blocked_direct", step_block)
            elif interpret or flux_update_fits(ny, nx):
                pallas_update = make_flux_update(
                    nzl, ny, nx, area, 1.0 / vol, interpret=interpret
                )
                dense_kind = ("plane",)
            if blocked_update is not None or pallas_update is not None:
                mx3 = jnp.asarray(mask_x, dtype).reshape(1, 1, nx)
                my3 = jnp.asarray(mask_y, dtype).reshape(1, ny, 1)


        # Negative-side x/y faces: the flux through cell i's negative face
        # equals the positive-side face flux of cell i-1, i.e.
        # jnp.roll(f, 1, axis) — the boundary mask is already baked into f.
        # Accumulation follows the general path's slot order (z-, y-, x-,
        # x+, y+, z+); negative-side face flux enters the cell with +,
        # positive-side leaves with - (solve.hpp:227-233).
        def blocked_step(rho, vx, vy, vz, v_lo, v_hi, mzu, mzd, dt):
            """One blocked-kernel step given the vz device-edge planes —
            shared by step() (planes rebuilt per call: vz is an input)
            and the multi-step run (planes hoisted out of the loop).
            rho's interior neighbor planes are read in-kernel through the
            direct index maps; only its two ppermute edge planes are
            produced here."""
            r_lo, r_hi = extend.planes(rho)
            return blocked_update(
                rho, r_lo, r_hi, vx, vy, vz, v_lo, v_hi, mx3, my3,
                mzu, mzd, dt,
            )

        def body(zf_up, zf_dn, rho, vx, vy, vz, dt):
            rho, vx, vy, vz = rho[0], vx[0], vy[0], vz[0]
            mz_up = zf_up[0][:, None, None]
            mz_dn = zf_dn[0][:, None, None]

            if blocked_update is not None:
                v_lo, v_hi = extend.planes(vz)
                new_rho = blocked_step(
                    rho, vx, vy, vz, v_lo, v_hi, mz_up, mz_dn, dt
                )
                return (new_rho[None],)

            rho_e = extend(rho)
            vz_e = extend(vz)

            if pallas_update is not None:
                new_rho = pallas_update(
                    rho_e, vx, vy, vz_e, mx3, my3, mz_up, mz_dn, dt,
                )
                return (new_rho[None],)

            fx = face_flux(rho, jnp.roll(rho, -1, 2), vx, jnp.roll(vx, -1, 2), area[0], dt) * mx
            fy = face_flux(rho, jnp.roll(rho, -1, 1), vy, jnp.roll(vy, -1, 1), area[1], dt) * my
            fz = face_flux(rho, rho_e[2:], vz, vz_e[2:], area[2], dt) * mz_up
            fz_dn = face_flux(rho_e[:-2], rho, vz_e[:-2], vz, area[2], dt) * mz_dn

            flux = fz_dn
            flux = flux + jnp.roll(fy, 1, 1)
            flux = flux + jnp.roll(fx, 1, 2)
            flux = flux - fx
            flux = flux - fy
            flux = flux - fz
            return ((rho + flux * dtype(1.0 / vol))[None],)

        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(data_spec, data_spec, data_spec, data_spec, data_spec, data_spec, P()),
            out_specs=(data_spec,),
            check_vma=False,
        )

        # z-face masks as runtime-argument tables (ROADMAP item 4): the
        # jitted bodies are table-content-independent; only the plain
        # wrappers below close over the device copies
        @jax.jit
        def step_fn(zf_up, zf_dn, state, dt):
            (new_rho,) = fn(
                zf_up, zf_dn,
                state["density"], state["vx"], state["vy"], state["vz"],
                jnp.asarray(dt, dtype),
            )
            return {**state, "density": new_rho}

        def step(state, dt):
            return step_fn(zf_up_dev, zf_dn_dev, state, dt)


        # Whole-block multi-step kernel (single device, block fits VMEM):
        # the entire run loop executes inside one kernel launch with zero
        # HBM traffic between steps — compute-bound instead of HBM-bound
        fused_run = None
        have_pallas = pallas_update is not None or blocked_update is not None
        if have_pallas and D == 1 and fused_run_fits(nzl, ny, nx):
            fused = make_fused_run(
                nzl, ny, nx, area, 1.0 / vol, interpret=interpret
            )
            mzu3 = jnp.asarray(zface_up[0], dtype).reshape(nzl, 1, 1)
            mzd3 = jnp.asarray(zface_dn[0], dtype).reshape(nzl, 1, 1)

            # face masks as runtime-argument tables (ROADMAP item 4):
            # the jitted body is table-content-independent — the masks
            # are plain pallas-kernel operands either way, so lifting
            # them through the jit boundary cannot perturb the kernel —
            # and only the plain wrapper closes over the device copies
            @jax.jit
            def fused_run_fn(masks, state, steps, dt):
                fmx, fmy, fmzu, fmzd = masks
                new_rho = fused(
                    state["density"][0], state["vx"][0], state["vy"][0],
                    state["vz"][0], fmx, fmy, fmzu, fmzd, dt, steps,
                )
                return {**state, "density": new_rho[None]}

            def fused_run(state, steps, dt):
                return fused_run_fn(
                    (mx3, my3, mzu3, mzd3), state, steps, dt)

        # Blocked multi-step run: the whole fori_loop inside one shard_map
        # so the constant vz halo stacks are built once per run call, not
        # once per step (the generic run path re-derives them every
        # iteration because the step body cannot know vz is loop-invariant)
        dense_run = None
        if blocked_update is not None:

            def run_body(zf_up, zf_dn, rho, vx, vy, vz, dt, steps):
                rho, vx, vy, vz = rho[0], vx[0], vy[0], vz[0]
                mzu = zf_up[0][:, None, None]
                mzd = zf_dn[0][:, None, None]
                v_lo, v_hi = extend.planes(vz)

                def one(i, r):
                    return blocked_step(
                        r, vx, vy, vz, v_lo, v_hi, mzu, mzd, dt
                    )

                out = jax.lax.fori_loop(0, steps, one, rho)
                return (out[None],)

            run_sm = shard_map(
                run_body,
                mesh=mesh,
                in_specs=(data_spec,) * 6 + (P(), P()),
                out_specs=(data_spec,),
                check_vma=False,
            )

            @jax.jit
            def dense_run_fn(zf_up, zf_dn, state, steps, dt):
                (new_rho,) = run_sm(
                    zf_up, zf_dn,
                    state["density"], state["vx"], state["vy"], state["vz"],
                    jnp.asarray(dt, dtype), jnp.asarray(steps, jnp.int32),
                )
                return {**state, "density": new_rho}

            def dense_run(state, steps, dt):
                return dense_run_fn(zf_up_dev, zf_dn_dev, state, steps, dt)

        dx = self._dx

        @jax.jit
        def max_dt(state):
            s = jnp.stack(
                [
                    dtype(dx[0]) / jnp.abs(state["vx"]),
                    dtype(dx[1]) / jnp.abs(state["vy"]),
                    dtype(dx[2]) / jnp.abs(state["vz"]),
                ],
                axis=-1,
            )
            s = jnp.where(jnp.isfinite(s) & (s > 0), s, jnp.inf)
            return jnp.min(s)


        # AMR refinement indicator on the dense layout (adapter.hpp:71-110
        # runs on the same data the solver uses — so does this): max
        # relative density difference to the 6 face neighbors as shifted
        # slices, with open-boundary faces masked out (the solver's own
        # mx/my masks; mxn/myn are their negative-side rolls) and z
        # through the slab halo ring
        mxp, myp = mx, my
        mxn = jnp.roll(mxp, 1, 2)
        myn = jnp.roll(myp, 1, 1)

        def md_body(zf_up, zf_dn, rho, thr):
            rho = rho[0]

            def rel(a, b):
                return jnp.abs(a - b) / (jnp.minimum(a, b) + thr)

            rho_e = extend(rho)
            md = rel(rho, jnp.roll(rho, -1, 2)) * mxp
            md = jnp.maximum(md, rel(rho, jnp.roll(rho, 1, 2)) * mxn)
            md = jnp.maximum(md, rel(rho, jnp.roll(rho, -1, 1)) * myp)
            md = jnp.maximum(md, rel(rho, jnp.roll(rho, 1, 1)) * myn)
            md = jnp.maximum(md, rel(rho, rho_e[2:]) * zf_up[0][:, None, None])
            md = jnp.maximum(md, rel(rho, rho_e[:-2]) * zf_dn[0][:, None, None])
            return (md[None],)

        fn_md = shard_map(
            md_body,
            mesh=mesh,
            in_specs=(data_spec, data_spec, data_spec, P()),
            out_specs=(data_spec,),
            check_vma=False,
        )

        @jax.jit
        def max_diff_fn(zf_up, zf_dn, state, diff_threshold):
            (md,) = fn_md(
                zf_up, zf_dn, state["density"],
                jnp.asarray(diff_threshold, dtype),
            )
            return {**state, "max_diff": md}

        def dense_max_diff(state, diff_threshold):
            return max_diff_fn(zf_up_dev, zf_dn_dev, state, diff_threshold)

        return {
            "step": step,
            "fused_run": fused_run,
            "dense_run": dense_run,
            "max_dt": max_dt,
            "max_diff": dense_max_diff,
            "dense_kind": dense_kind,
        }

    def _dense_to_rows(self, state):
        """Dense [D, nzl, ny, nx] state -> general [D, R] row-layout state
        (vectorized per field)."""
        grid = self.grid
        cells = grid.get_cells()
        row_state = grid.new_state(self.spec)
        for name in self.spec:
            vals = self.get_cell_data(state, name, cells)
            row_state = grid.set_cell_data(row_state, name, cells, vals)
        return row_state

    def _dense_coords(self, ids):
        """(device, local z, y, x) of given cell ids in the dense layout."""
        ids = np.asarray(ids, dtype=np.uint64)
        i = self.dense
        lin = (ids - np.uint64(1)).astype(np.int64)
        x = lin % i.nx
        y = (lin // i.nx) % i.ny
        z = lin // (i.nx * i.ny)
        return z // i.nz_local, z % i.nz_local, y, x

    # ----------------------------------------------------------- user API

    def initialize_state(self):
        """Rotating-hump initial condition (initialize.hpp:36-80): solid-body
        rotation about the domain center, cosine density hump."""
        grid = self.grid
        cells = grid.get_cells()
        centers = grid.geometry.get_center(cells)
        vx = -centers[:, 1] + 0.5
        vy = centers[:, 0] - 0.5
        vz = np.zeros(len(cells))
        radius = 0.15
        r = np.minimum(
            np.sqrt((centers[:, 0] - 0.25) ** 2 + (centers[:, 1] - 0.5) ** 2), radius
        ) / radius
        rho = 0.25 * (1 + np.cos(np.pi * r))

        if self.dense is not None:
            from ..parallel.mesh import shard_spec

            i = self.dense
            shape = (i.n_devices, i.nz_local, i.ny, i.nx)
            state = {}
            for name in self.spec:
                state[name] = jnp.zeros(shape, dtype=self.dtype)
            d, zl, y, x = self._dense_coords(cells)
            for name, vals in (("density", rho), ("vx", vx), ("vy", vy), ("vz", vz)):
                host = np.zeros(shape, dtype=self.dtype)
                host[d, zl, y, x] = vals
                state[name] = jax.device_put(
                    jnp.asarray(host), shard_spec(self.grid.mesh, 4)
                )
            return state

        state = grid.new_state(self.spec)
        state = grid.set_cell_data(state, "vx", cells, vx)
        state = grid.set_cell_data(state, "vy", cells, vy)
        state = grid.set_cell_data(state, "vz", cells, vz)
        state = grid.set_cell_data(state, "density", cells, rho)
        # ghosts need velocities once (the reference transfers all data at
        # init); densities refresh every step
        state = self._exchange(state)
        return state

    def get_cell_data(self, state, field: str, ids):
        """Layout-aware per-cell read (dense or row layout)."""
        if self.dense is not None:
            d, zl, y, x = self._dense_coords(ids)
            return fetch(state[field])[d, zl, y, x]
        return self.grid.get_cell_data(state, field, ids)

    def set_cell_data(self, state, field: str, ids, values):
        if self.dense is not None:
            from ..parallel.mesh import shard_spec

            d, zl, y, x = self._dense_coords(ids)
            host = fetch(state[field]).copy()
            host[d, zl, y, x] = values
            return {
                **state,
                field: jax.device_put(
                    jnp.asarray(host), shard_spec(self.grid.mesh, 4)
                ),
            }
        return self.grid.set_cell_data(state, field, ids, values)

    def step(self, state, dt):
        return self._step(state, dt)

    def _wide_spec(self):
        """Exchange-amortized step split (ISSUE 14): one full-depth
        default-hood density exchange funds ``budget`` interior steps.
        Stencil relevance is ``"face"`` — the flux kernel masks every
        non-face entry to an exact 0.0 via ``face_dir``, so a depth-g
        default hood funds g face-stencil steps even though corner
        neighbors of deep ghost rows are absent on the replica.  Ghost
        velocities are valid forever (``initialize_state`` ends with a
        full-state exchange and the fields are static), so only density
        staleness meters the budget."""
        from ..parallel.exec_cache import WideStepSpec, traced_jit
        from ..parallel.mesh import put_table
        from ..parallel.wide_halo import get_wide_plan, wide_enabled

        if not wide_enabled() or self.tables is None:
            return None
        cached = getattr(self, "_wide_cached", None)
        if cached is not None and cached[0] is self.grid.epoch:
            return cached[1]
        plan = get_wide_plan(self.grid, self.hood_id, relevance="face")
        spec = None
        if plan.budget >= 2:
            wex = self.grid.halo(None)
            wex_body = wex.raw_body
            wrings = tuple(wex.ring_send) + tuple(wex.ring_recv)
            mesh = self.grid.mesh
            _, wdev = build_face_tables(
                self.grid, self.hood_id, self.tables, self.dtype,
                hood_arrays=(plan.nbr_offset, plan.nbr_len,
                             plan.nbr_rows, plan.nbr_valid),
            )
            wt = dict(wdev)
            wt["nbr_rows"] = put_table(plan.nbr_rows, mesh)
            wt["steps_ok"] = put_table(plan.steps_ok, mesh)

            def build():
                def interior(wt, state, dt, j):
                    rho = state["density"]
                    nbr = wt["nbr_rows"]
                    rho_n = gather_neighbors(rho, nbr)
                    vx_n = gather_neighbors(state["vx"], nbr)
                    vy_n = gather_neighbors(state["vy"], nbr)
                    vz_n = gather_neighbors(state["vz"], nbr)

                    sgn = jnp.sign(wt["face_dir"]).astype(rho.dtype)
                    ai = wt["axis_idx"]
                    v_cell = jnp.where(
                        ai == 0, state["vx"][..., None],
                        jnp.where(ai == 1, state["vy"][..., None],
                                  state["vz"][..., None]),
                    )
                    v_nbr = jnp.where(
                        ai == 0, vx_n, jnp.where(ai == 1, vy_n, vz_n)
                    )
                    cl, nl = wt["cell_axis_len"], wt["nbr_axis_len"]
                    v_face = (cl * v_nbr + nl * v_cell) / (cl + nl)

                    upwind_pos = jnp.where(
                        v_face >= 0, rho[..., None], rho_n
                    )
                    upwind_neg = jnp.where(
                        v_face >= 0, rho_n, rho[..., None]
                    )
                    upwind = jnp.where(sgn > 0, upwind_pos, upwind_neg)
                    face_flux = upwind * dt * v_face * wt["min_area"]
                    contrib = jnp.where(
                        wt["face_dir"] != 0, -sgn * face_flux, 0.0
                    )
                    flux = ordered_sum(contrib, axis=-1) * wt["inv_volume"]

                    # live = rows whose stencil inputs are still exact at
                    # interior step j; identical flux math as the fused
                    # step over bitwise-equal table rows, so live local
                    # rows match the exchange-every-step path exactly
                    live = wt["steps_ok"] > j
                    new_rho = jnp.where(live, rho + flux, rho)
                    return {**state, "density": new_rho,
                            "flux": jnp.zeros_like(flux)}

                return traced_jit("advection.wide_step", interior)

            fn = self.grid.exec_cache.get(
                ("advection.wide_step", wex.structure_key,
                 str(np.dtype(self.dtype))), build
            )
            spec = WideStepSpec(
                exchange=lambda args, wargs, state: {
                    **state,
                    **wex_body(*wargs[0], {"density": state["density"]}),
                },
                interior=lambda args, wargs, state, dt, j: fn(
                    wargs[1], state, dt, j
                ),
                budget=plan.budget,
                args=(wrings, wt),
                local_mask=plan.local_mask,
            )
        self._wide_cached = (self.grid.epoch, spec)
        return spec

    def batch_step_spec(self):
        """This model's step entry point in cohort-batchable form
        (ISSUE 9): the compiled member program plus its runtime-argument
        tables, so ``dccrg_tpu/serve`` can stack many same-signature
        scenarios on a leading axis and vmap one jitted cohort body over
        them.  Works for the dense fast path (tables are closed-over
        pure functions of the kernel key) and both general gather forms
        (tables ride along per member as stacked arguments).  The
        spec's ``steps_per_dispatch`` declares the default deep-dispatch
        depth (``DCCRG_ENSEMBLE_K``, ISSUE 11): the serving tier wraps
        ``call`` in a device-side ``fori_loop`` advancing that many
        interior steps per host dispatch — each step's halo exchange
        runs inside the loop body, so the in-kernel protocol is
        identical to ``step`` called k times."""
        from ..parallel.exec_cache import (
            BatchStepSpec,
            default_steps_per_dispatch,
        )

        k = default_steps_per_dispatch()
        dtype = np.dtype(self.dtype)
        if self.dense is not None:
            step = self._step
            return BatchStepSpec(
                kind="advection.dense", kernel_key=self._dense_key,
                call=lambda args, state, dt: step(state, dt),
                args=(), dt_dtype=dtype, steps_per_dispatch=k,
            )
        wide = self._wide_spec()
        if self.overlap:
            fn = self._split_fn
            return BatchStepSpec(
                kind="advection.split",
                kernel_key=self._kernel_key("advection.split_step"),
                call=lambda args, state, dt: fn(*args, state, dt),
                args=self._split_args, dt_dtype=dtype,
                steps_per_dispatch=k, wide=wide,
            )
        fn = self._step_fn
        return BatchStepSpec(
            kind="advection",
            kernel_key=self._kernel_key("advection.step"),
            call=lambda args, state, dt: fn(*args, state, dt),
            args=(self._rings, self.tables.tree(), self._dev),
            dt_dtype=dtype, steps_per_dispatch=k, wide=wide,
        )

    def _record_run(self, path: str, steps, state) -> None:
        """Post-run reconciliation (obs.fused): the whole-run paths keep
        their ghost traffic inside jit, so the host seam sees nothing —
        record ``steps x schedule bytes`` once per dispatch instead."""
        from ..obs import fused

        if not self.grid.telemetry.enabled:
            return
        try:
            bps = self.grid.halo(None).bytes_moved(
                {"density": state["density"]}
            )
        except Exception:  # noqa: BLE001 — telemetry must never raise
            bps = 0
        fused.record_run("advection", path, steps, bps)

    def run(self, state, steps: int, dt):
        """Advance ``steps`` timesteps in a single device-side loop
        (``lax.fori_loop``) — one dispatch for the whole run, the
        compiler-friendly form of the reference's while-loop driver
        (2d.cpp:321+).  Use this for tight stepping; ``step`` for loops
        interleaved with host logic (AMR, load balancing, IO)."""
        if getattr(self, "_fused_run", None) is not None:
            self._record_run("fused", steps, state)
            return self._fused_run(
                state, jnp.asarray(steps, jnp.int32), jnp.asarray(dt, self.dtype)
            )
        if (
            getattr(self, "_prefer_boxed", False)
            and getattr(self, "_boxed_run", None) is not None
        ):
            self._record_run("boxed", steps, state)
            return self._boxed_run(
                state, jnp.asarray(steps, jnp.int32), jnp.asarray(dt, self.dtype)
            )
        if getattr(self, "_flat_run", None) is not None:
            # the flat kernel is an optimization; if the TPU compiler
            # rejects it (op support varies by generation), fall back to
            # the boxed/general dispatch permanently for this instance —
            # but only after the fallback succeeds on the same inputs
            # (utils/fallback.py's policy), so a caller error propagates
            self._record_run("flat", steps, state)
            return fallback_call(
                "flat AMR kernel",
                lambda: self._flat_run(
                    state, jnp.asarray(steps, jnp.int32),
                    jnp.asarray(dt, self.dtype),
                ),
                lambda: self._run_general(state, steps, dt),
                self._disable_flat,
            )
        return self._run_general(state, steps, dt)

    def _disable_flat(self):
        self._flat_run = None

    def _run_general(self, state, steps, dt):
        """The non-flat whole-run dispatch: boxed, dense, or the general
        gather-path fori_loop."""
        if getattr(self, "_boxed_run", None) is not None:
            self._record_run("boxed", steps, state)
            return self._boxed_run(
                state, jnp.asarray(steps, jnp.int32), jnp.asarray(dt, self.dtype)
            )
        if getattr(self, "_dense_run", None) is not None:
            self._record_run("dense", steps, state)
            return self._dense_run(
                state, jnp.asarray(steps, jnp.int32), jnp.asarray(dt, self.dtype)
            )
        if not hasattr(self, "_run"):
            from ..parallel.exec_cache import (
                record_run_donation,
                run_donate_enabled,
            )

            donate = run_donate_enabled()

            def probe_wrap(dispatch):
                """Measure donation effectiveness per dispatch via the
                ``is_deleted`` probe, like the ensemble's stacked-state
                donation path."""
                if not donate:
                    return dispatch

                def wrapped(state, steps, dt):
                    probe = state["density"]
                    out = dispatch(state, steps, dt)
                    record_run_donation("advection", probe)
                    return out

                return wrapped

            if getattr(self, "_split_fn", None) is not None:
                from ..parallel.exec_cache import traced_jit

                inner = self._split_fn

                def build():
                    def run_fn(rings, ti, to, local, state, steps, dt):
                        return jax.lax.fori_loop(
                            0, steps,
                            lambda i, st: inner(rings, ti, to, local, st,
                                                dt),
                            state,
                        )

                    # state is positional arg 4; donation joins the
                    # cache key so flipping DCCRG_RUN_DONATE re-keys
                    return traced_jit(
                        "advection.split_run", run_fn,
                        donate_argnums=(4,) if donate else (),
                    )

                fn = self.grid.exec_cache.get(
                    self._kernel_key("advection.split_run") + (donate,),
                    build,
                )
                args = self._split_args
                self._run = probe_wrap(lambda state, steps, dt: fn(
                    *args, state, steps, dt
                ))
            elif hasattr(self, "_step_fn"):
                from ..parallel.exec_cache import traced_jit

                inner = self._step_fn

                def build():
                    def run_fn(rings, t, dev, state, steps, dt):
                        return jax.lax.fori_loop(
                            0, steps,
                            lambda i, st: inner(rings, t, dev, st, dt),
                            state,
                        )

                    # state is positional arg 3
                    return traced_jit(
                        "advection.run", run_fn,
                        donate_argnums=(3,) if donate else (),
                    )

                fn = self.grid.exec_cache.get(
                    self._kernel_key("advection.run") + (donate,), build
                )
                rings, t, dev = self._rings, self.tables.tree(), self._dev
                self._run = probe_wrap(lambda state, steps, dt: fn(
                    rings, t, dev, state, steps, dt
                ))
            else:
                # dense XLA-only path: the step came from the cached
                # dense bundle (plain (state, dt) signature)
                inner = self._step

                @jax.jit
                def run_fn(state, steps, dt):
                    return jax.lax.fori_loop(
                        0, steps, lambda i, st: inner(st, dt), state
                    )

                self._run = run_fn
        self._record_run("split" if self.overlap else "general",
                         steps, state)
        return self._run(state, steps, jnp.asarray(dt, self.dtype))

    def max_time_step(self, state) -> float:
        return float(self._max_dt(state))

    def compute_max_diff(self, state, diff_threshold: float):
        """AMR refinement indicator on whatever layout the model runs
        (dense shifted-slice or general gather) — no rebuild needed to
        decide adaptation, matching the reference running its indicator on
        the solver's own data (adapter.hpp:71-110)."""
        return self._max_diff(state, diff_threshold)

    # --------------------------------------------------------- AMR driver

    def check_for_adaptation(
        self,
        state,
        diff_increase: float = 0.025,
        diff_threshold: float = 0.25,
        unrefine_sensitivity: float = 0.5,
    ):
        """The reference's adaptation criterion (adapter.hpp:47-178): refine
        where the max relative density difference to face neighbors exceeds
        (level+1)*diff_increase, unrefine where it falls below
        unrefine_sensitivity times that; queues requests on the grid."""
        grid = self.grid
        if grid.mapping.max_refinement_level == 0:
            return state
        state = self.compute_max_diff(state, diff_threshold)
        cells = grid.get_cells()
        md = self.get_cell_data(state, "max_diff", cells)
        lvl = grid.mapping.get_refinement_level(cells)
        refine_diff = (lvl + 1) * diff_increase
        unrefine_diff = unrefine_sensitivity * refine_diff
        # bulk request storms (grid.py: identical queue state to the
        # scalar per-cell calls, vectorized)
        grid.refine_completely_many(cells[md > refine_diff])
        hold = (md <= refine_diff) & (md >= unrefine_diff)
        grid.dont_unrefine_many(cells[hold & (lvl > 0)])
        grid.unrefine_completely_many(cells[(md < unrefine_diff) & (lvl > 0)])
        return state

    def adapt_grid(self, state):
        """Commit queued adaptation and carry the state over: children
        inherit the parent's density, new parents average their children
        (adapter.hpp:230-292); velocities are re-derived from the rotation
        field at the new cell centers (adapter.hpp:300-310).  Returns a NEW
        Advection bound to the new grid structure plus the remapped state."""
        grid = self.grid
        if self.dense is not None:
            # decide from the GLOBAL queues: another controller may have
            # queued requests this process hasn't seen (sync is idempotent
            # and called symmetrically on every process)
            from ..utils.collectives import sync_adaptation

            sync_adaptation(grid.amr)
            if not (grid.amr.to_refine or grid.amr.to_unrefine):
                # nothing queued anywhere: the grid stays uniform, the
                # (empty) commit keeps the current epoch, and this model —
                # dense tables, jitted kernels and all — remains valid; a
                # no-op adapt cycle must not degrade or recompile anything
                new_cells = grid.stop_refining(presynced=True)
                return self, state, new_cells, grid.get_removed_cells()
            # the dense z-slab layout is about to stop existing (the grid
            # refines): convert to the row layout remap_state speaks,
            # while the pre-commit epoch is still current
            state = self._dense_to_rows(state)
            new_cells = grid.stop_refining(presynced=True)
        else:
            new_cells = grid.stop_refining()
        removed = grid.get_removed_cells()
        state = grid.remap_state(
            state,
            policy={
                "density": {"refine": "inherit", "unrefine": "mean"},
                "flux": {"refine": "zero", "unrefine": "zero"},
                "max_diff": {"refine": "zero", "unrefine": "zero"},
            },
        )
        adv = Advection(grid, self.hood_id, self.dtype, allow_dense=False)
        cells = grid.get_cells()
        centers = grid.geometry.get_center(cells)
        state = grid.set_cell_data(state, "vx", cells, -centers[:, 1] + 0.5)
        state = grid.set_cell_data(state, "vy", cells, centers[:, 0] - 0.5)
        state = grid.set_cell_data(state, "vz", cells, np.zeros(len(cells)))
        state = adv._exchange(state)
        return adv, state, new_cells, removed

    def total_mass(self, state) -> float:
        if self.dense is not None:
            return float(fetch(state["density"], dtype=np.float64).sum() * self._vol)
        rho = fetch(state["density"])
        vol = 1.0 / np.where(self.inv_volume > 0, self.inv_volume, np.inf)
        local = np.asarray(self.tables.local_mask)
        return float((rho * vol * local).sum())
