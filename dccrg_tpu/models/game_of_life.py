"""Conway's game of life on the distributed grid — the framework's
"hello world", matching the reference's
``examples/simple_game_of_life.cpp`` / ``examples/game_of_life.cpp``:
full-vertex neighborhood, count live neighbors of every local cell after a
ghost update, then apply the 2/3 rule.

The per-cell loop of the reference becomes one jitted array program: a
neighbor gather + masked reduction feeding an elementwise rule, sharded over
the device mesh with the halo exchange fused into the same XLA computation.

With ``overlap=True`` the step is the split-phase form of the reference's
canonical overlap pattern (``examples/game_of_life.cpp:124-138``): launch
the ghost collective, count neighbors of INNER cells (no remote
neighbors — no data dependence on the transfer, so XLA's latency-hiding
scheduler runs them concurrently), merge the ghosts, then count the OUTER
cells.  Inner/outer row sets are compacted per device, so the split also
computes exactly the local cells instead of all rows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.stencil import StencilTables, compact_rows, gather_neighbors
from ..utils.fallback import fallback_call

__all__ = ["GameOfLife"]


def _life_rule(count, alive):
    """The 2/3 rule (examples/simple_game_of_life.cpp:95-106)."""
    return jnp.where(
        count == 3,
        jnp.uint32(1),
        jnp.where(count != 2, jnp.uint32(0), alive),
    )


class GameOfLife:
    #: the payload declaration — the reference's ``game_of_life_cell`` with
    #: its ``get_mpi_datatype`` seam (examples/simple_game_of_life.cpp:20-32)
    SPEC = {
        "is_alive": ((), np.uint32),
        "live_neighbor_count": ((), np.uint32),
    }

    def __init__(self, grid, hood_id=None, overlap: bool = False,
                 allow_dense: bool = True, use_pallas=True):
        #: use_pallas follows the Advection convention: True = compiled
        #: kernels on TPU only; "interpret" = force the Pallas
        #: interpreter (CI/CPU integration coverage); False = XLA only
        self.use_pallas = use_pallas
        self.grid = grid
        self.hood_id = hood_id
        self._exchange = grid.halo(hood_id)
        if overlap:
            # the overlap step derives compacted tables straight from the
            # epoch; the full [D, R, K] StencilTables would sit unused
            self.tables = None
            self._step = self._build_overlap_step()
        else:
            self.tables = StencilTables(grid, hood_id)
            self._step = self._build_step()
        # overlap=True exists to exercise/measure the split-phase step, so
        # it keeps the per-step loop
        from ..parallel.dense import detect_dense2d

        self.dense2d = (
            detect_dense2d(grid, hood_id) if allow_dense and not overlap
            else None
        )
        #: whole-run fused Pallas kernel (set by _build_dense_run when it
        #: qualifies); _dense_run is the XLA dense loop beneath it
        self._fused_run = None
        self._dense_run = (
            self._build_dense_run() if self.dense2d is not None else None
        )

    def new_state(self, alive_cells=()):
        state = self.grid.new_state(self.SPEC)
        if len(alive_cells):
            state = self.grid.set_cell_data(
                state,
                "is_alive",
                np.asarray(alive_cells, dtype=np.uint64),
                np.ones(len(alive_cells), dtype=np.uint32),
            )
        return state

    def _build_step(self):
        from ..parallel.exec_cache import traced_jit

        ex = self._exchange
        ex_body = ex.raw_body
        rings = tuple(ex.ring_send) + tuple(ex.ring_recv)

        def build():
            def step(rings, tables, state):
                state = ex_body(*rings, state)
                alive = state["is_alive"]
                nbr_alive = gather_neighbors(
                    alive, tables["nbr_rows"]
                )                                                   # [D,R,K]
                # dtype pinned to the SPEC's uint32 (like the overlap
                # step): without it jnp.sum promotes to uint64 under
                # x64, so the step's OUTPUT state has a different aval
                # than its input and the second dispatch of any program
                # taking the state re-traces once
                count = jnp.sum(
                    jnp.where(tables["nbr_valid"],
                              (nbr_alive > 0).astype(jnp.uint32), 0),
                    axis=-1, dtype=jnp.uint32,
                )
                new_alive = _life_rule(count, alive)
                local = tables["local_mask"]
                return {
                    "is_alive": jnp.where(local, new_alive, alive),
                    "live_neighbor_count": jnp.where(
                        local, count, jnp.uint32(0)
                    ),
                }

            return traced_jit("gol.step", step)

        fn = self.grid.exec_cache.get(("gol.step", ex.structure_key), build)
        tables = self.tables.tree()
        self._step_fn = fn
        self._step_args = (rings, tables)
        return lambda state: fn(rings, tables, state)

    def _build_overlap_step(self):
        """Split-phase step: collective and inner compute are dataflow-
        independent inside one XLA program; outer compute depends on the
        merged ghosts.  Bit-identical results to the blocking step."""
        from ..utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel.mesh import SHARD_AXIS, put_table, shard_spec

        from ..parallel.shapes import bucket_rows

        grid = self.grid
        epoch = grid.epoch
        hood = epoch.hoods[self.hood_id]
        halo = self._exchange
        scratch = epoch.R - 1
        D = epoch.n_devices
        ar = np.arange(D)[:, None]
        # compacted widths ride the bucket ladder with grid-persistent
        # hints (see models/advection.py build_split_tables): churn must
        # not retrace the fused body while the signature holds
        hints = getattr(grid, "_ring_hints", {})

        def rows_of(side, mask):
            natural = max(int(mask.sum(axis=1).max()) if D else 0, 1)
            key = (self.hood_id, f"split.{side}", 0)
            W = bucket_rows(natural, hints.get(key))
            hints[key] = W
            return compact_rows(mask, scratch, width=W)

        irows = rows_of("inner", hood.inner_mask)            # [D, Wi]
        orows = rows_of("outer", hood.outer_mask)            # [D, Wo]
        # gather tables restricted to the compacted row sets
        nri, nvi = hood.nbr_rows[ar, irows], hood.nbr_valid[ar, irows]
        nro, nvo = hood.nbr_rows[ar, orows], hood.nbr_valid[ar, orows]
        mesh = grid.mesh
        put = lambda a: put_table(a, mesh)
        tabs = tuple(put(a) for a in (irows, orows, nri, nvi, nro, nvo))
        local = put(epoch.local_mask)
        rings = tuple(halo.ring_send) + tuple(halo.ring_recv)
        ks = tuple(halo.ring_ks)
        # backend-selected transport (collective ppermute or Pallas
        # async-DMA ring), a pure function of halo.structure_key
        ring_start = halo.make_ring_start()

        from ..parallel.exec_cache import traced_jit
        from ..parallel.halo import HaloExchange

        def build():
            nk = len(ks)
            data_spec = P(SHARD_AXIS)
            rule = _life_rule

            def body(*args):
                # args: ring send tabs (nk), ring recv tabs (nk), then
                # the compute tables and the alive array
                sends = [a[0] for a in args[:nk]]
                recvs = [a[0] for a in args[nk:2 * nk]]
                irows, orows, nri, nvi, nro, nvo, local, alive = (
                    args[2 * nk:]
                )
                a = alive[0]                                     # [R]
                # --- start: ghost payloads in flight (depend on `a`)
                payloads = ring_start(a, sends)
                # --- inner compute: no remote neighbors, no dep on
                # payloads
                cnt_i = jnp.sum(
                    jnp.where(nvi[0], (a[nri[0]] > 0).astype(jnp.uint32),
                              0),
                    -1, dtype=jnp.uint32,
                )
                new_i = rule(cnt_i, a[irows[0]])
                # --- wait: merging the payloads IS the synchronization
                a2 = HaloExchange.ring_finish(a, recvs, payloads)
                # --- outer compute: needs fresh ghosts
                cnt_o = jnp.sum(
                    jnp.where(nvo[0],
                              (a2[nro[0]] > 0).astype(jnp.uint32), 0),
                    -1, dtype=jnp.uint32,
                )
                new_o = rule(cnt_o, a2[orows[0]])
                out_a = a2.at[irows[0]].set(new_i).at[orows[0]].set(new_o)
                out_a = jnp.where(local[0], out_a, a2)   # clean scratch
                cnt = (
                    jnp.zeros_like(a)
                    .at[irows[0]].set(cnt_i).at[orows[0]].set(cnt_o)
                )
                cnt = jnp.where(local[0], cnt, jnp.uint32(0))
                return out_a[None], cnt[None]

            fn = shard_map(
                body,
                mesh=mesh,
                in_specs=(P(SHARD_AXIS, None),) * (2 * nk)
                + (P(SHARD_AXIS, None),) * 2
                + (P(SHARD_AXIS, None, None),) * 4
                + (P(SHARD_AXIS, None), data_spec),
                out_specs=(data_spec, data_spec),
                check_vma=False,
            )

            def step(rings, tabs, local, alive):
                return fn(*rings, *tabs, local, alive)

            return traced_jit("gol.overlap_step", step)

        fn = self.grid.exec_cache.get(
            ("gol.overlap_step", halo.structure_key), build
        )
        self._overlap_fn = fn
        self._overlap_args = (rings, tabs, local)

        def step(state):
            out_a, cnt = fn(rings, tabs, local, state["is_alive"])
            return {"is_alive": out_a, "live_neighbor_count": cnt}

        return step

    def _build_dense_run(self):
        """Whole-run device-side loop on the dense y-slab layout: the
        8-neighbor count is three shifted row bands x three x-rolls, the
        halo two ppermuted boundary rows — one dispatch for any number of
        turns (the reference's scalability configuration,
        ``tests/game_of_life/scalability.cpp``, without its per-turn
        message machinery).

        The bundle is a pure function of (mesh, dims, periodicity,
        pallas mode), so it is cached under that key and survives
        rebuilds that return to the same uniform shape."""
        from ..parallel.exec_cache import mesh_key

        info = self.dense2d
        pallas_mode = (self.use_pallas if isinstance(self.use_pallas, str)
                       else bool(self.use_pallas))
        key = ("gol.dense", mesh_key(self.grid.mesh), info["D"],
               info["nyl"], info["nx"],
               tuple(bool(p) for p in info["periodic"]), pallas_mode)
        fused, run = self.grid.exec_cache.get(key, self._build_dense_bundle)
        self._fused_run = fused
        return run

    def _build_dense_bundle(self):
        from ..utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        from ..parallel.dense import HaloExtend
        from ..parallel.mesh import SHARD_AXIS

        info = self.dense2d
        nx, nyl, D = info["nx"], info["nyl"], info["D"]
        per = nyl * nx
        px, py = info["periodic"]
        mesh = self.grid.mesh
        ring = HaloExtend(D)

        # single device + VMEM fit: the whole run in one Pallas launch
        from ..ops.dense_advection import have_pallas, pallas_available
        from ..ops.gol_kernel import gol_run_fits, make_gol_run

        interpret = self.use_pallas == "interpret"
        fused_run = None
        if (
            self.use_pallas
            and have_pallas()
            and D == 1
            and gol_run_fits(nyl, nx)
            and (interpret or pallas_available(np.float32))
        ):
            from ..ops.flat_amr import pad_extent

            # tile-align both axes when the pad fits VMEM (x: 128 lanes,
            # y: 8 sublanes) — the reference example's 500x500 board
            # becomes 504x512 and every per-turn roll is aligned
            nxp, nyp = pad_extent(nx, 128), pad_extent(nyl, 8)
            if not gol_run_fits(nyp, nxp):
                # near the VMEM ceiling: drop the costlier x pad first,
                # keeping the nearly-free sublane alignment if it fits
                nxp = nx
                if not gol_run_fits(nyp, nxp):
                    nyp = nyl
            kern = make_gol_run(
                nyl, nx, px, py,
                ny_pad=nyp if nyp != nyl else None,
                nx_pad=nxp if nxp != nx else None,
                interpret=interpret,
            )

            @jax.jit
            def fused_fn(state, turns):
                a = state["is_alive"][0, :per].reshape(nyl, nx)
                out, cnt = kern((a > 0).astype(jnp.float32), turns)
                out_a = state["is_alive"][0].at[:per].set(
                    out.reshape(-1).astype(jnp.uint32)
                )
                out_c = jnp.zeros_like(out_a).at[:per].set(
                    cnt.reshape(-1).astype(jnp.uint32)
                )
                return {
                    "is_alive": out_a[None],
                    "live_neighbor_count": out_c[None],
                }

            # the Pallas kernel is an optimization over the XLA dense
            # loop built below — keep both so a TPU-generation Mosaic
            # rejection at first call can fall back (see run())
            fused_run = fused_fn
        # x-wrap validity columns: neighbor at x+1 invalid for x = nx-1 on
        # open x; at x-1 invalid for x = 0
        vx_hi = np.ones(nx, np.uint32)
        vx_lo = np.ones(nx, np.uint32)
        if not px:
            vx_hi[-1] = 0
            vx_lo[0] = 0
        vx_of = {-1: jnp.asarray(vx_lo), 0: None, 1: jnp.asarray(vx_hi)}

        def body(alive_rows, turns):
            a0 = alive_rows[0, :per].reshape(nyl, nx)
            dev = jax.lax.axis_index(SHARD_AXIS)
            # boundary-row validity on open y: device 0's below-row and
            # device D-1's above-row come from the ring wrap and must be
            # dropped
            ok_below = jnp.uint32(1 if py else 0) | (dev != 0).astype(jnp.uint32)
            ok_above = jnp.uint32(1 if py else 0) | (dev != D - 1).astype(jnp.uint32)

            def one(carry):
                a, _ = carry
                below, above = ring.planes(a)
                ext = jnp.concatenate(
                    [below * ok_below, a, above * ok_above], axis=0
                )
                cnt = jnp.zeros((nyl, nx), jnp.uint32)
                for dy in (0, 1, 2):
                    band = (ext[dy:dy + nyl] > 0).astype(jnp.uint32)
                    for dx in (-1, 0, 1):
                        if dy == 1 and dx == 0:
                            continue
                        t = jnp.roll(band, -dx, 1) if dx else band
                        v = vx_of[dx]
                        cnt = cnt + (t * v[None, :] if v is not None else t)
                return _life_rule(cnt, a), cnt

            a, cnt = jax.lax.fori_loop(
                0, turns, lambda i, c: one(c), (a0, jnp.zeros_like(a0))
            )
            out_a = alive_rows[0].at[:per].set(a.reshape(-1))
            out_c = jnp.zeros_like(out_a).at[:per].set(cnt.reshape(-1))
            return out_a[None], out_c[None]

        fn = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS), P()),
            out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            check_vma=False,
        )

        @jax.jit
        def run_fn(state, turns):
            out_a, cnt = fn(state["is_alive"], turns)
            return {"is_alive": out_a, "live_neighbor_count": cnt}

        return fused_run, run_fn

    def _disable_fused(self):
        self._fused_run = None

    def step(self, state):
        return self._step(state)

    def _wide_spec(self):
        """Exchange-amortized step split (ISSUE 14).  The life rule reads
        the WHOLE neighborhood, so stencil relevance is ``"all"``: on the
        default hood the budget collapses to 1 (the rule genuinely has
        the hood's radius) and wide stepping disengages; amortization
        engages when this model steps on a radius-1 sub-hood of a deeper
        default hood — the exchange then refills the full-depth ghost
        zone while ``steps_ok`` meters its shell-by-shell consumption."""
        from ..parallel.exec_cache import WideStepSpec, traced_jit
        from ..parallel.mesh import put_table
        from ..parallel.wide_halo import get_wide_plan, wide_enabled

        if not wide_enabled():
            return None
        cached = getattr(self, "_wide_cached", None)
        if cached is not None and cached[0] is self.grid.epoch:
            return cached[1]
        plan = get_wide_plan(self.grid, self.hood_id, relevance="all")
        spec = None
        if plan.budget >= 2:
            wex = self.grid.halo(None)
            wex_body = wex.raw_body
            wrings = tuple(wex.ring_send) + tuple(wex.ring_recv)
            mesh = self.grid.mesh
            wtabs = {
                "nbr_rows": put_table(plan.nbr_rows, mesh),
                "nbr_valid": put_table(plan.nbr_valid, mesh),
                "steps_ok": put_table(plan.steps_ok, mesh),
                "local_mask": put_table(plan.local_mask, mesh),
            }

            def build():
                def interior(wtabs, state, j):
                    alive = state["is_alive"]
                    nbr_alive = gather_neighbors(
                        alive, wtabs["nbr_rows"]
                    )
                    count = jnp.sum(
                        jnp.where(wtabs["nbr_valid"],
                                  (nbr_alive > 0).astype(jnp.uint32), 0),
                        axis=-1, dtype=jnp.uint32,
                    )
                    new_alive = _life_rule(count, alive)
                    live = wtabs["steps_ok"] > j
                    # local rows (live through the whole budget) match
                    # the blocking step bitwise: same gather/count/rule
                    # over identical table rows; the stale fringe keeps
                    # its exchanged values
                    return {
                        "is_alive": jnp.where(live, new_alive, alive),
                        "live_neighbor_count": jnp.where(
                            live & wtabs["local_mask"], count,
                            jnp.where(live, jnp.uint32(0),
                                      state["live_neighbor_count"]),
                        ),
                    }

                return traced_jit("gol.wide_step", interior)

            fn = self.grid.exec_cache.get(
                ("gol.wide_step", wex.structure_key), build
            )
            spec = WideStepSpec(
                exchange=lambda args, wargs, state: wex_body(
                    *wargs[0], state
                ),
                interior=lambda args, wargs, state, dt, j: fn(
                    wargs[1], state, j
                ),
                budget=plan.budget,
                args=(wrings, wtabs),
                local_mask=plan.local_mask,
            )
        self._wide_cached = (self.grid.epoch, spec)
        return spec

    def batch_step_spec(self):
        """Cohort-batchable step entry point (ISSUE 9; see
        ``Advection.batch_step_spec``).  GoL takes no dt — the cohort's
        per-member dt operand is ignored.  ``steps_per_dispatch``
        declares the deep-dispatch default (ISSUE 11)."""
        from ..parallel.exec_cache import (
            BatchStepSpec,
            default_steps_per_dispatch,
        )

        k = default_steps_per_dispatch()
        ex = self._exchange
        wide = self._wide_spec()
        if self.tables is None:          # overlap=True split-phase form
            fn = self._overlap_fn

            def call(args, state, dt):
                out_a, cnt = fn(args[0], args[1], args[2],
                                state["is_alive"])
                return {"is_alive": out_a, "live_neighbor_count": cnt}

            return BatchStepSpec(
                kind="gol.overlap",
                kernel_key=("gol.overlap_step", ex.structure_key),
                call=call, args=self._overlap_args,
                steps_per_dispatch=k, wide=wide,
            )
        fn = self._step_fn
        return BatchStepSpec(
            kind="gol", kernel_key=("gol.step", ex.structure_key),
            call=lambda args, state, dt: fn(args[0], args[1], state),
            args=self._step_args, steps_per_dispatch=k, wide=wide,
        )

    def run(self, state, turns: int, sync_every: int = 16):
        """Advance ``turns`` steps.  On the dense 2-D fast path the whole
        run is one device-side loop (single dispatch).  Otherwise the
        dispatch queue is drained every ``sync_every`` turns: unbounded
        async pipelines of collective programs trip XLA:CPU's rendezvous
        watchdog on oversubscribed hosts (virtual-device meshes), and a
        depth-16 pipeline already hides dispatch latency on real chips."""
        if self._fused_run is not None and turns > 0:
            self._record_run("fused", turns, state)
            return fallback_call(
                "fused GoL kernel", self._fused_run, self._dense_run,
                self._disable_fused, state, jnp.asarray(turns, jnp.int32),
            )
        if self._dense_run is not None and turns > 0:
            self._record_run("dense", turns, state)
            return self._dense_run(state, jnp.asarray(turns, jnp.int32))
        for i in range(turns):
            state = self._step(state)
            if sync_every and (i + 1) % sync_every == 0:
                jax.block_until_ready(state)
        return state

    def _record_run(self, path: str, turns, state) -> None:
        """Whole-run dispatches keep their ghost traffic inside jit —
        reconcile ``turns x schedule bytes`` on the host (obs.fused).
        Only ``is_alive`` crosses the wire, like the reference's
        ``get_mpi_datatype`` (examples/simple_game_of_life.cpp:20-32)."""
        from ..obs import fused

        if not self.grid.telemetry.enabled:
            return
        try:
            bps = self._exchange.bytes_moved(
                {"is_alive": state["is_alive"]}
            )
        except Exception:  # noqa: BLE001 — telemetry must never raise
            bps = 0
        fused.record_run("game_of_life", path, turns, bps)

    def alive_cells(self, state) -> np.ndarray:
        cells = self.grid.get_cells()
        alive = self.grid.get_cell_data(state, "is_alive", cells)
        return cells[alive > 0]
