"""Conway's game of life on the distributed grid — the framework's
"hello world", matching the reference's
``examples/simple_game_of_life.cpp`` / ``examples/game_of_life.cpp``:
full-vertex neighborhood, count live neighbors of every local cell after a
ghost update, then apply the 2/3 rule.

The per-cell loop of the reference becomes one jitted array program: a
neighbor gather + masked reduction feeding an elementwise rule, sharded over
the device mesh with the halo exchange fused into the same XLA computation.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.stencil import StencilTables, gather_neighbors

__all__ = ["GameOfLife"]


class GameOfLife:
    #: the payload declaration — the reference's ``game_of_life_cell`` with
    #: its ``get_mpi_datatype`` seam (examples/simple_game_of_life.cpp:20-32)
    SPEC = {
        "is_alive": ((), np.uint32),
        "live_neighbor_count": ((), np.uint32),
    }

    def __init__(self, grid, hood_id=None):
        self.grid = grid
        self.hood_id = hood_id
        self.tables = StencilTables(grid, hood_id)
        self._exchange = grid.halo(hood_id)
        self._step = self._build_step()

    def new_state(self, alive_cells=()):
        state = self.grid.new_state(self.SPEC)
        if len(alive_cells):
            state = self.grid.set_cell_data(
                state,
                "is_alive",
                np.asarray(alive_cells, dtype=np.uint64),
                np.ones(len(alive_cells), dtype=np.uint32),
            )
        return state

    def _build_step(self):
        tables = self.tables.tree()
        exchange = self._exchange

        @jax.jit
        def step(state):
            state = exchange(state)
            alive = state["is_alive"]
            nbr_alive = gather_neighbors(alive, tables["nbr_rows"])     # [D,R,K]
            count = jnp.sum(
                jnp.where(tables["nbr_valid"], (nbr_alive > 0).astype(jnp.uint32), 0),
                axis=-1,
            )
            new_alive = jnp.where(
                count == 3,
                jnp.uint32(1),
                jnp.where(count != 2, jnp.uint32(0), alive),
            )
            local = tables["local_mask"]
            return {
                "is_alive": jnp.where(local, new_alive, alive),
                "live_neighbor_count": jnp.where(local, count, jnp.uint32(0)),
            }

        return step

    def step(self, state):
        return self._step(state)

    def run(self, state, turns: int):
        for _ in range(turns):
            state = self._step(state)
        return state

    def alive_cells(self, state) -> np.ndarray:
        cells = self.grid.get_cells()
        alive = self.grid.get_cell_data(state, "is_alive", cells)
        return cells[alive > 0]
