"""Particle-in-cell support: variable-size per-cell payloads.

Reference: ``tests/particles`` — each cell owns a list of particle
coordinates; ``get_mpi_datatype`` switches between transferring the count
and the coordinates (2-phase ragged exchange,
``tests/particles/cell.hpp:50-84``, ``simple.cpp:285-294``), and particles
that leave a cell are handed to whichever cell now contains them
(``simple.cpp:52-97``).

TPU-native formulation: ragged lists become padded ``[D, R, P, 3]`` arrays
plus an ``[D, R]`` count — the padding-based ragged-buffer strategy the
build plan prescribes.  The push is a jitted array op; the ghost update
moves counts first and coordinates second through the same halo engine
(both are exact copies).  Re-bucketing particles into their new cells is
fully device-side on uniform-Cartesian grids — refined, mixed-periodicity,
and arbitrarily partitioned included: a per-device sort over the padded
slots inside ``shard_map``, keyed on the epoch's sorted row-id tables via
the jittable cell-id algebra, claims the particles of local + ghost rows
that land in this device's own cells (the array form of the reference's
neighbor handoff), with ``run()`` advancing whole histories in one
dispatch; stretched geometries re-bucket through the host path, like
every structural mutation in this design.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import SHARD_AXIS, put_table, shard_spec
from ..parallel.stencil import StencilTables
from ..utils.collectives import fetch

__all__ = ["Particles"]


class Particles:
    def __init__(self, grid, max_particles_per_cell: int = 64, hood_id=None,
                 dtype=None):
        self.grid = grid
        self.P = int(max_particles_per_cell)
        self.hood_id = hood_id
        # coordinate dtype: f64 where x64 is enabled (the reference stores
        # doubles), otherwise f32 up front — requesting f64 under default
        # jax settings would silently truncate with a warning per alloc
        if dtype is None:
            import jax

            dtype = np.float64 if jax.config.jax_enable_x64 else np.float32
        self.dtype = np.dtype(dtype)
        self.tables = StencilTables(grid, hood_id)
        self._exchange = grid.halo(hood_id)
        self._push = self._build_push()
        self._dev_rebucket = self._build_device_rebucket()

    def spec(self):
        return {
            "particles": ((self.P, 3), self.dtype),
            "number_of_particles": ((), np.int32),
        }

    # ------------------------------------------------------------ lifecycle

    def new_state(self, positions: np.ndarray):
        """Bucket given particle positions (M, 3) into their cells."""
        state = self.grid.new_state(self.spec())
        return self._scatter(state, np.asarray(positions, dtype=np.float64))

    def _scatter(self, state, positions):
        """Bucket (M, 3) positions into their cells' padded slots — one
        sort + one scatter, no per-particle Python (the reference's
        per-particle list appends, ``tests/particles/simple.cpp:52-97``,
        become array ops)."""
        grid = self.grid
        D, R = grid.n_devices, grid.epoch.R
        pos_arr = np.zeros((D, R, self.P, 3))
        cnt = np.zeros((D, R), dtype=np.int32)
        if len(positions):
            cells = grid.get_existing_cell(positions)
            if not (cells != 0).all():
                raise ValueError("particles outside the grid")
            lpos = grid.leaves.position(cells)
            dev = grid.leaves.owner[lpos].astype(np.int64)
            row = grid.epoch.row_of[lpos].astype(np.int64)
            key = dev * R + row
            cnt_flat = np.bincount(key, minlength=D * R)
            if cnt_flat.max() > self.P:
                raise ValueError(
                    f"cell capacity exceeded ({self.P} particles/cell)"
                )
            cnt = cnt_flat.reshape(D, R).astype(np.int32)
            # stable sort groups particles by cell, preserving input order
            # within each cell; the slot is the rank within the group
            from ..utils.setops import ragged_arange

            order = np.argsort(key, kind="stable")
            ks = key[order]
            slot = ragged_arange(cnt_flat[cnt_flat > 0])
            pos_arr.reshape(D * R, self.P, 3)[ks, slot] = positions[order]
        put = lambda a: jax.device_put(
            jnp.asarray(a), shard_spec(self.grid.mesh, np.ndim(a))
        )
        return {
            **state,
            "particles": put(pos_arr),
            "number_of_particles": put(cnt),
        }

    # ---------------------------------------------------------------- step

    def _build_push(self):
        from ..parallel.exec_cache import traced_jit

        def build():
            def push(local, state, velocity, dt):
                P = state["particles"].shape[2]
                slot = jnp.arange(P, dtype=jnp.int32)[None, None, :]
                valid = slot < state["number_of_particles"][..., None]
                v = jnp.asarray(velocity)
                if v.ndim == 3:          # per-cell field [D, R, 3]
                    v = v[:, :, None, :]
                moved = state["particles"] + v * dt
                new = jnp.where(
                    (valid & local[..., None])[..., None], moved,
                    state["particles"],
                )
                return {**state, "particles": new}

            return traced_jit("particles.push", push)

        fn = self.grid.exec_cache.get(("particles.push",), build)
        local = self.tables.local_mask
        self._push_fn, self._push_args = fn, (local,)
        return lambda state, velocity, dt: fn(local, state, velocity, dt)

    # --------------------------------------------- device-side re-bucketing

    def _build_device_rebucket(self):
        """Jitted re-bucket keyed on the epoch's leaf tables: per device,
        one sort of the padded slots keys particles by target local row;
        ghost rows supply the neighbors' emigrants (so the CFL-style
        constraint is the halo width, exactly the reference's
        neighbor-handoff reach, ``tests/particles/simple.cpp:52-97``).

        The target cell of a position is found with the id algebra
        (``core/mapping.py``): the candidate cell id at every refinement
        level is pure shift/add arithmetic on the max-resolution voxel
        triple, and exactly one candidate can appear in this device's
        sorted row-id table (leaves are disjoint) — so AMR grids and any
        post-``balance_load`` ownership stay on device.  Mixed
        periodicity is handled per axis; a particle escaping through a
        non-periodic boundary or out-running the ghost halo is dropped
        and counted in the state's ``overflow`` scalar, as is capacity
        overflow of a cell's ``P`` slots.

        Returns None when the grid does not qualify (stretched geometry,
        whose per-cell sizes the voxel arithmetic cannot express, or an
        id space past the integer width jax can use) — the host path
        stays the general mechanism."""
        from ..utils.compat import shard_map
        from jax.sharding import PartitionSpec as Pspec

        grid = self.grid
        epoch = grid.epoch
        mapping = epoch.mapping
        leaves = grid.leaves
        N = len(leaves)
        if N == 0:
            return None
        # uniform Cartesian only: the device path buckets by a single
        # level-0 cell size, which a stretched geometry does not have
        if not getattr(grid.geometry, "uniform_level0", False):
            return None
        D, R, P = epoch.n_devices, epoch.R, self.P
        # candidate ids (and the dead-row sentinels past them) must fit
        # the device integer width: int32 always works on TPU; int64
        # needs jax x64 mode
        if int(mapping.last_cell) + R + 2 < 2**31:
            id_dtype = jnp.int32
        elif jax.config.jax_enable_x64 and int(mapping.last_cell) + R + 2 < 2**62:
            id_dtype = jnp.int64
        else:
            return None
        L = mapping.max_refinement_level
        geo = grid.geometry
        nx, ny, nz = (int(v) for v in mapping.length)
        start = np.asarray(geo.get_start(), np.float64)
        clen0 = np.asarray(geo.get_level_0_cell_length(), np.float64)
        dom = clen0 * np.array([nx, ny, nz], np.float64)
        # voxel = max-refinement-resolution index (the mapping's unit)
        vox_len = clen0 / (1 << L)
        vox_dims = np.array([nx << L, ny << L, nz << L], np.int64)
        periodic = np.asarray(grid.topology.periodic, dtype=bool)
        level_offsets = mapping._level_offsets.astype(np.int64)  # [L+2]

        # per-device sorted row-id table: dead rows (id 0) get a sentinel
        # past every real id so they sort last and never match
        cell_ids = np.asarray(epoch.cell_ids).astype(np.int64)   # [D, R]
        sentinel = int(mapping.last_cell) + 1
        keyed = np.where(cell_ids == 0, sentinel + np.arange(R)[None, :],
                         cell_ids)
        sort_order = np.argsort(keyed, axis=1)
        ids_sorted = np.take_along_axis(keyed, sort_order, axis=1)
        rows_sorted = sort_order.astype(np.int32)
        local_rows = np.asarray(self.tables.local_mask)          # [D, R]
        # only levels that actually occur need a candidate search
        levels_present = sorted(
            int(v) for v in
            np.unique(mapping.get_refinement_level(leaves.cells))
        )

        def body(pos, cnt, ids_s, rows_s, local):
            pos, cnt = pos[0], cnt[0]                 # [R,P,3], [R]
            ids_s, rows_s, local = ids_s[0], rows_s[0], local[0]
            R, P = pos.shape[0], pos.shape[1]
            dt_ = pos.dtype
            valid = (jnp.arange(P, dtype=jnp.int32)[None, :]
                     < cnt[:, None]).reshape(-1)
            p = pos.reshape(R * P, 3)
            # the domain is CLOSED ([start, end] per axis), exactly like
            # the host path's geometry: a coordinate sitting on the upper
            # edge belongs to the last cell, so wrap a periodic axis only
            # when the raw coordinate is strictly outside (a plain mod
            # would fold end onto start and diverge from the host bucket)
            lo = jnp.asarray(start, dt_)
            hi = jnp.asarray(start + dom, dt_)
            raw_in = (p >= lo) & (p <= hi)
            wrapped = lo + jnp.mod(p - lo, jnp.asarray(dom, dt_))
            wp = jnp.where(jnp.asarray(periodic) & ~raw_in, wrapped, p)
            # only a non-periodic axis can lose a particle
            in_dom = (jnp.asarray(periodic) | raw_in).all(axis=1)
            rel = (wp - lo) / jnp.asarray(vox_len, dt_)
            ivox = jnp.floor(rel).astype(id_dtype)
            ivox = jnp.clip(ivox, 0, jnp.asarray(vox_dims - 1, id_dtype))
            # candidate cell id at each level PRESENT in the leaf set:
            # shift the voxel triple to level resolution, linearize
            # x-fastest, add the level block offset
            # (mapping.get_cell_from_indices, jittable form)
            row = jnp.zeros(R * P, jnp.int32)
            found = jnp.zeros(R * P, bool)
            for lvl in levels_present:
                s = L - lvl
                cx, cy, cz = ivox[:, 0] >> s, ivox[:, 1] >> s, ivox[:, 2] >> s
                lx = id_dtype(nx << lvl)
                ly = id_dtype(ny << lvl)
                cand = id_dtype(level_offsets[lvl]) + cx + lx * (cy + ly * cz)
                pos_s = jnp.searchsorted(ids_s, cand)
                hit = ids_s[jnp.minimum(pos_s, R - 1)] == cand
                row = jnp.where(hit & ~found,
                                rows_s[jnp.minimum(pos_s, R - 1)], row)
                found = found | hit
            claimed = valid & in_dom & found & local[row]
            key = jnp.where(claimed, row, R)          # R = drop sentinel
            order = jnp.argsort(key)
            ks = key[order]
            ws = wp[order]
            slot = (jnp.arange(R * P, dtype=jnp.int32)
                    - jnp.searchsorted(ks, ks, side="left"))
            counts = jnp.zeros(R + 1, jnp.int32).at[key].add(1)[:R]
            new_pos = (
                jnp.zeros((R, P, 3), dt_)
                .at[ks, slot]
                .set(ws, mode="drop")
            )
            new_cnt = jnp.minimum(counts, P)
            # lost = canonical population before (local rows only; ghost
            # rows are duplicates) minus population after — catches
            # capacity overflow, non-periodic escapes, and particles that
            # out-ran the ghost halo (the device path's reach limit, like
            # the reference's neighbor handoff)
            before = jax.lax.psum(
                jnp.sum(cnt * local, dtype=jnp.int32), SHARD_AXIS
            )
            after = jax.lax.psum(
                jnp.sum(new_cnt, dtype=jnp.int32), SHARD_AXIS
            )
            return new_pos[None], new_cnt[None], before - after

        from ..parallel.exec_cache import mesh_key, traced_jit

        def build():
            fn = shard_map(
                body,
                mesh=grid.mesh,
                in_specs=(Pspec(SHARD_AXIS),) * 5,
                out_specs=(Pspec(SHARD_AXIS), Pspec(SHARD_AXIS), Pspec()),
                check_vma=False,
            )

            def rebucket_fn(ids_arr, rows_arr, local_arr, state):
                new_pos, new_cnt, lost = fn(
                    state["particles"], state["number_of_particles"],
                    ids_arr, rows_arr, local_arr,
                )
                return {
                    **state,
                    "particles": new_pos,
                    "number_of_particles": new_cnt,
                    "overflow": state.get("overflow", jnp.int32(0)) + lost,
                }

            return traced_jit("particles.rebucket", rebucket_fn)

        # every constant baked into the body's trace (voxel metrics,
        # level offsets, periodicity, the present refinement levels) is
        # pinned by this key; the sorted row-id tables enter as runtime
        # arguments, so churn that keeps the key re-dispatches the
        # compiled program
        key = (
            "particles.rebucket", mesh_key(grid.mesh), D,
            str(np.dtype(id_dtype)), L, (nx, ny, nz),
            tuple(np.asarray(start, np.float64).tolist()),
            tuple(np.asarray(clen0, np.float64).tolist()),
            tuple(bool(p) for p in periodic), tuple(levels_present),
        )
        fn = self.grid.exec_cache.get(key, build)
        ids_arr = put_table(ids_sorted, grid.mesh, id_dtype)
        rows_arr = put_table(rows_sorted, grid.mesh, jnp.int32)
        local_arr = put_table(local_rows, grid.mesh, bool)
        self._rebucket_fn = fn
        self._rebucket_key = key
        self._rebucket_args = (ids_arr, rows_arr, local_arr)
        return lambda state: fn(ids_arr, rows_arr, local_arr, state)

    def velocity_field(self, fn) -> np.ndarray:
        """Per-cell velocity array ``[D, R, 3]`` from a function of cell
        centers (``fn((M, 3)) -> (M, 3)``) — the reference's per-cell
        velocity data (``tests/particles/simple.cpp:52-97``) as one dense
        field the push broadcasts over each cell's particles."""
        ids = np.asarray(self.grid.epoch.cell_ids)
        D, R = ids.shape
        out = np.zeros((D, R, 3))
        live = ids.ravel() != 0
        if live.any():
            centers = self.grid.geometry.get_center(ids.ravel()[live])
            out.reshape(D * R, 3)[live] = np.asarray(fn(centers))
        return out

    def step(self, state, velocity=(0.1, 0.0, 0.0), dt: float = 1.0):
        """Push particles, refresh ghost copies (counts then coordinates —
        the reference's 2-phase idiom), then hand particles to the cells
        that now contain them.  ``velocity`` is a global (3,) vector or a
        per-cell ``[D, R, 3]`` field (see ``velocity_field``).  On
        qualifying grids every phase is device-side — no host transfer."""
        state = self._push(state, np.asarray(velocity, dtype=np.float64), dt)
        # phase 1: counts; phase 2: coordinates
        state = {**state, **self._exchange({"number_of_particles": state["number_of_particles"]})}
        state = {**state, **self._exchange({"particles": state["particles"]})}
        return self.rebucket(state)

    def run(self, state, steps: int, velocity=(0.1, 0.0, 0.0),
            dt: float = 1.0):
        """Advance ``steps`` push/exchange/re-bucket cycles in ONE
        device-side loop (requires the device re-bucket path; falls back
        to per-step host orchestration otherwise)."""
        if self._dev_rebucket is None:
            for _ in range(int(steps)):
                state = self.step(state, velocity, dt)
            return state
        if not hasattr(self, "_run"):
            from ..parallel.exec_cache import traced_jit

            ex = self._exchange
            ex_body = ex.raw_body
            rings = tuple(ex.ring_send) + tuple(ex.ring_recv)
            push_fn, rebucket_fn = self._push_fn, self._rebucket_fn

            def build():
                def run_fn(rings, local, rb_args, state, steps,
                           velocity, dt):
                    def one(_, st):
                        st = push_fn(local, st, velocity, dt)
                        st = {**st, **ex_body(*rings, {
                            "number_of_particles":
                                st["number_of_particles"],
                        })}
                        st = {**st, **ex_body(
                            *rings, {"particles": st["particles"]}
                        )}
                        return rebucket_fn(*rb_args, st)

                    return jax.lax.fori_loop(0, steps, one, state)

                return traced_jit("particles.run", run_fn)

            fn = self.grid.exec_cache.get(
                ("particles.run", ex.structure_key, self._rebucket_key),
                build,
            )
            rb_args = self._rebucket_args
            local = self._push_args[0]
            self._run = lambda state, steps, velocity, dt: fn(
                rings, local, rb_args, state, steps, velocity, dt
            )
        state = {**state, "overflow": state.get("overflow", jnp.int32(0))}
        return self._run(
            state, jnp.asarray(steps, jnp.int32),
            jnp.asarray(np.asarray(velocity, dtype=np.float64)),
            jnp.asarray(dt),
        )

    def rebucket(self, state):
        """Reassignment of particles to the cells that contain them
        (periodic wrapping included) — the device sort path when the grid
        qualifies, host-orchestrated otherwise."""
        if self._dev_rebucket is not None:
            return self._dev_rebucket(state)
        positions = self.positions(state)
        wrapped = self.grid.geometry.get_real_coordinate(positions)
        if np.isnan(wrapped).any():
            raise ValueError("particle left a non-periodic boundary")
        return self._scatter(state, wrapped)

    # ------------------------------------------------------------- queries

    def positions(self, state) -> np.ndarray:
        """All particles of local cells, (M, 3), in (device, row, slot)
        order — one boolean gather, no per-row Python."""
        pos = fetch(state["particles"])
        cnt = fetch(state["number_of_particles"])
        local = np.asarray(self.tables.local_mask)
        valid = (
            np.arange(self.P)[None, None, :] < cnt[..., None]
        ) & local[..., None]
        return pos[valid]

    def count(self, state) -> int:
        cnt = fetch(state["number_of_particles"])
        return int((cnt * np.asarray(self.tables.local_mask)).sum())

    def particles_of(self, state, cell) -> np.ndarray:
        pos = int(self.grid.leaves.position(np.uint64(cell)))
        d = int(self.grid.leaves.owner[pos])
        r = int(self.grid.epoch.row_of[pos])
        n = int(fetch(state["number_of_particles"])[d, r])
        return fetch(state["particles"])[d, r, :n]

    def remap(self, state):
        """Carry particles across a structural change (AMR or load
        balance): simply re-bucket every particle into the current grid —
        the array-level equivalent of the reference shipping unrefined
        cells' particle lists to their parents."""
        pts = self.positions(state)  # read with the OLD layout's tables
        self.tables = StencilTables(self.grid, self.hood_id)
        self._exchange = self.grid.halo(self.hood_id)
        self._push = self._build_push()
        self._dev_rebucket = self._build_device_rebucket()
        if hasattr(self, "_run"):
            del self._run
        fresh = self.grid.new_state(self.spec())
        if "overflow" in state:
            fresh["overflow"] = state["overflow"]
        return self._scatter(fresh, pts)
