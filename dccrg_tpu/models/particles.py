"""Particle-in-cell support: variable-size per-cell payloads.

Reference: ``tests/particles`` — each cell owns a list of particle
coordinates; ``get_mpi_datatype`` switches between transferring the count
and the coordinates (2-phase ragged exchange,
``tests/particles/cell.hpp:50-84``, ``simple.cpp:285-294``), and particles
that leave a cell are handed to whichever cell now contains them
(``simple.cpp:52-97``).

TPU-native formulation: ragged lists become padded ``[D, R, P, 3]`` arrays
plus an ``[D, R]`` count — the padding-based ragged-buffer strategy the
build plan prescribes.  The push is a jitted array op; the ghost update
moves counts first and coordinates second through the same halo engine
(both are exact copies); re-bucketing particles into their new cells is
host-orchestrated per step, like every structural mutation in this design.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import shard_spec
from ..parallel.stencil import StencilTables

__all__ = ["Particles"]


class Particles:
    def __init__(self, grid, max_particles_per_cell: int = 64, hood_id=None):
        self.grid = grid
        self.P = int(max_particles_per_cell)
        self.hood_id = hood_id
        self.tables = StencilTables(grid, hood_id)
        self._exchange = grid.halo(hood_id)
        self._push = self._build_push()

    def spec(self):
        return {
            "particles": ((self.P, 3), np.float64),
            "number_of_particles": ((), np.int32),
        }

    # ------------------------------------------------------------ lifecycle

    def new_state(self, positions: np.ndarray):
        """Bucket given particle positions (M, 3) into their cells."""
        state = self.grid.new_state(self.spec())
        return self._scatter(state, np.asarray(positions, dtype=np.float64))

    def _scatter(self, state, positions):
        """Bucket (M, 3) positions into their cells' padded slots — one
        sort + one scatter, no per-particle Python (the reference's
        per-particle list appends, ``tests/particles/simple.cpp:52-97``,
        become array ops)."""
        grid = self.grid
        D, R = grid.n_devices, grid.epoch.R
        pos_arr = np.zeros((D, R, self.P, 3))
        cnt = np.zeros((D, R), dtype=np.int32)
        if len(positions):
            cells = grid.get_existing_cell(positions)
            if not (cells != 0).all():
                raise ValueError("particles outside the grid")
            lpos = grid.leaves.position(cells)
            dev = grid.leaves.owner[lpos].astype(np.int64)
            row = grid.epoch.row_of[lpos].astype(np.int64)
            key = dev * R + row
            cnt_flat = np.bincount(key, minlength=D * R)
            if cnt_flat.max() > self.P:
                raise ValueError(
                    f"cell capacity exceeded ({self.P} particles/cell)"
                )
            cnt = cnt_flat.reshape(D, R).astype(np.int32)
            # stable sort groups particles by cell, preserving input order
            # within each cell; the slot is the rank within the group
            from ..utils.setops import ragged_arange

            order = np.argsort(key, kind="stable")
            ks = key[order]
            slot = ragged_arange(cnt_flat[cnt_flat > 0])
            pos_arr.reshape(D * R, self.P, 3)[ks, slot] = positions[order]
        put = lambda a: jax.device_put(
            jnp.asarray(a), shard_spec(self.grid.mesh, np.ndim(a))
        )
        return {
            **state,
            "particles": put(pos_arr),
            "number_of_particles": put(cnt),
        }

    # ---------------------------------------------------------------- step

    def _build_push(self):
        local = self.tables.local_mask

        @jax.jit
        def push(state, velocity, dt):
            slot = jnp.arange(self.P)[None, None, :]
            valid = slot < state["number_of_particles"][..., None]
            v = jnp.asarray(velocity)
            if v.ndim == 3:          # per-cell field [D, R, 3]
                v = v[:, :, None, :]
            moved = state["particles"] + v * dt
            new = jnp.where(
                (valid & local[..., None])[..., None], moved, state["particles"]
            )
            return {**state, "particles": new}

        return push

    def velocity_field(self, fn) -> np.ndarray:
        """Per-cell velocity array ``[D, R, 3]`` from a function of cell
        centers (``fn((M, 3)) -> (M, 3)``) — the reference's per-cell
        velocity data (``tests/particles/simple.cpp:52-97``) as one dense
        field the push broadcasts over each cell's particles."""
        ids = np.asarray(self.grid.epoch.cell_ids)
        D, R = ids.shape
        out = np.zeros((D, R, 3))
        live = ids.ravel() != 0
        if live.any():
            centers = self.grid.geometry.get_center(ids.ravel()[live])
            out.reshape(D * R, 3)[live] = np.asarray(fn(centers))
        return out

    def step(self, state, velocity=(0.1, 0.0, 0.0), dt: float = 1.0):
        """Push particles, refresh ghost copies (counts then coordinates —
        the reference's 2-phase idiom), then hand particles to the cells
        that now contain them.  ``velocity`` is a global (3,) vector or a
        per-cell ``[D, R, 3]`` field (see ``velocity_field``)."""
        state = self._push(state, np.asarray(velocity, dtype=np.float64), dt)
        # phase 1: counts; phase 2: coordinates
        state = {**state, **self._exchange({"number_of_particles": state["number_of_particles"]})}
        state = {**state, **self._exchange({"particles": state["particles"]})}
        return self.rebucket(state)

    def rebucket(self, state):
        """Host-orchestrated reassignment of particles to the cells that
        contain them (periodic wrapping included)."""
        positions = self.positions(state)
        wrapped = self.grid.geometry.get_real_coordinate(positions)
        if np.isnan(wrapped).any():
            raise ValueError("particle left a non-periodic boundary")
        return self._scatter(state, wrapped)

    # ------------------------------------------------------------- queries

    def positions(self, state) -> np.ndarray:
        """All particles of local cells, (M, 3), in (device, row, slot)
        order — one boolean gather, no per-row Python."""
        pos = np.asarray(state["particles"])
        cnt = np.asarray(state["number_of_particles"])
        local = np.asarray(self.tables.local_mask)
        valid = (
            np.arange(self.P)[None, None, :] < cnt[..., None]
        ) & local[..., None]
        return pos[valid]

    def count(self, state) -> int:
        cnt = np.asarray(state["number_of_particles"])
        return int((cnt * np.asarray(self.tables.local_mask)).sum())

    def particles_of(self, state, cell) -> np.ndarray:
        pos = int(self.grid.leaves.position(np.uint64(cell)))
        d = int(self.grid.leaves.owner[pos])
        r = int(self.grid.epoch.row_of[pos])
        n = int(np.asarray(state["number_of_particles"])[d, r])
        return np.asarray(state["particles"])[d, r, :n]

    def remap(self, state):
        """Carry particles across a structural change (AMR or load
        balance): simply re-bucket every particle into the current grid —
        the array-level equivalent of the reference shipping unrefined
        cells' particle lists to their parents."""
        pts = self.positions(state)  # read with the OLD layout's tables
        self.tables = StencilTables(self.grid, self.hood_id)
        self._exchange = self.grid.halo(self.hood_id)
        self._push = self._build_push()
        fresh = self.grid.new_state(self.spec())
        return self._scatter(fresh, pts)
