"""Vlasiator-style Vlasov advection: a velocity-space block per spatial
cell — BASELINE's stretch configuration ("large f(v) block per spatial
cell"), the payload shape of the Vlasiator space-plasma code that the
reference grid underlies (reference CREDITS:4-6).

Solves df/dt + v·∇_x f = 0: each velocity bin advects through space with
its own constant velocity.  Payload per cell is the flattened [B = nv³]
distribution block; the step is the dimension-split upwind scheme of the
advection workload applied to every bin at once — on TPU this turns the
reference's per-cell block loops into one fused [D, nz, ny, nx, B] array
program where B rides the vectorized minor dimension.

Uniform slab-partitioned grids use the dense layout (parallel/dense.py)
with fused Pallas kernels and a dimension-SPLIT update (x, then y, then
z per step — the TPU-efficient form).  AMR or arbitrarily-partitioned
grids run the general row-layout path over the gather tables — the
reference's actual Vlasiator shape (an AMR spatial grid with one
velocity block per leaf) — pricing all faces UNSPLIT so each bin's
update is exactly the oracle-validated advection step with that bin's
constant velocity (the only available correctness anchor for 2:1 AMR
faces).  The two layouts therefore differ by the O(dt) splitting error
(tests pin the convergence); mass is conserved exactly on both.  Either
way the halo moves whole f(v) blocks (B doubles per ghost cell), which
is exactly the bandwidth profile the Vlasiator use case stresses.

Boundaries follow ``grid.topology``: periodic dimensions wrap; open
dimensions use vacuum inflow (f = 0 outside the domain) with free
outflow, the standard open-boundary closure for an upwind scheme — mass
then decreases monotonically as phase-space density leaves the box.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.dense import HaloExtend
from ..parallel.mesh import SHARD_AXIS, shard_spec
from ..utils.collectives import fetch
from ..utils.fallback import fallback_call

__all__ = ["Vlasov"]


class Vlasov:
    def __init__(self, grid, nv: int = 4, v_max: float = 1.0,
                 dtype=np.float32, use_pallas=True, overlap: bool = False):
        self.grid = grid
        #: split-phase stepping (ISSUE 7): run the general gather-path
        #: update as the fused start → interior → finish → boundary body.
        #: Forces the row layout even on slab grids — the split form
        #: exists to overlap the halo seam, which the dense ring hides
        #: inside its own shard_map.
        self.overlap = bool(overlap)
        self.info = grid.epoch.dense if not overlap else None
        self.nv = nv
        self.v_max = float(v_max)
        self.B = nv**3
        self.dtype = dtype
        self.use_pallas = use_pallas
        centers = (np.arange(nv) + 0.5) / nv * 2 * v_max - v_max
        vz, vy, vx = np.meshgrid(centers, centers, centers, indexing="ij")
        #: velocity of each bin, [B, 3]
        self.v_bins = np.stack([vx.ravel(), vy.ravel(), vz.ravel()], axis=-1)
        if self.info is not None:
            self._build_step()
        else:
            # AMR / non-slab grids: the general row-layout path — one
            # f(v) block per leaf over the gather tables, the
            # Vlasiator-on-dccrg configuration (AMR spatial grid with a
            # velocity block per cell)
            self._fused_block = 0
            self._build_general_step()

    def spec(self):
        return {"f": ((self.B,), self.dtype)}

    # ------------------------------------------------------------- kernels

    def _build_step(self):
        """Dense-layout kernels, cached as one bundle: every compiled
        artifact is a pure function of (mesh, dims, periodicity, cell
        size, velocity grid, dtype, pallas mode)."""
        from ..parallel.exec_cache import mesh_key

        info = self.info
        l0 = self.grid.geometry.get_level_0_cell_length()
        pallas_mode = (self.use_pallas if isinstance(self.use_pallas, str)
                       else bool(self.use_pallas))
        key = (
            "vlasov.dense", mesh_key(self.grid.mesh), info.n_devices,
            info.nz_local, info.ny, info.nx, self.nv, self.v_max,
            tuple(bool(p) for p in info.periodic),
            str(np.dtype(self.dtype)), pallas_mode,
            tuple(np.asarray(l0, np.float64).tolist()),
        )
        self._dense_key = key
        bundle = self.grid.exec_cache.get(key, self._build_dense_bundle)
        self._fused_block = bundle["fused_block"]
        self._step_xla, self._run_xla = bundle["step_xla"], bundle["run_xla"]
        self._step, self._run = bundle["step"], bundle["run"]

    def _build_dense_bundle(self) -> dict:
        from ..utils.compat import shard_map
        from jax.sharding import PartitionSpec as P

        info = self.info
        grid = self.grid
        dtype = self.dtype
        D = info.n_devices
        l0 = grid.geometry.get_level_0_cell_length()
        inv_dx = (1.0 / l0).astype(np.float64)
        extend = HaloExtend(info)
        v = jnp.asarray(self.v_bins, dtype)          # [B, 3]
        mesh = grid.mesh
        data_spec = P(SHARD_AXIS)

        def split_dim(f, f_lo, f_hi, vd, inv_dxd, dt, axis):
            """One dimension's upwind update for all bins.  f: [nzl, ny,
            nx, B]; f_lo/f_hi: neighbor values on the low/high side."""
            flux_hi = jnp.where(vd >= 0, f, f_hi) * vd      # at i+1/2
            flux_lo = jnp.where(vd >= 0, f_lo, f) * vd      # at i-1/2
            return f - dt * inv_dxd * (flux_hi - flux_lo)

        periodic = tuple(bool(p) for p in info.periodic)

        def body(f, dt):
            f = f[0]                                  # [nzl, ny, nx, B]
            # x and y wrap inside the block; open dimensions get vacuum
            # inflow (zero the wrapped-in plane) per grid.topology
            f_lo, f_hi = jnp.roll(f, 1, 2), jnp.roll(f, -1, 2)
            if not periodic[0]:
                f_lo = f_lo.at[:, :, 0].set(0)
                f_hi = f_hi.at[:, :, -1].set(0)
            f = split_dim(f, f_lo, f_hi, v[:, 0], dtype(inv_dx[0]), dt, 2)
            f_lo, f_hi = jnp.roll(f, 1, 1), jnp.roll(f, -1, 1)
            if not periodic[1]:
                f_lo = f_lo.at[:, 0].set(0)
                f_hi = f_hi.at[:, -1].set(0)
            f = split_dim(f, f_lo, f_hi, v[:, 1], dtype(inv_dx[1]), dt, 1)
            # z goes through the slab halo ring; for an open z boundary the
            # ring's wrap-around planes on the first/last device are vacuum
            fe = extend(f)
            if not periodic[2]:
                d = jax.lax.axis_index(SHARD_AXIS)
                fe = fe.at[0].multiply(jnp.where(d == 0, 0, 1).astype(dtype))
                fe = fe.at[-1].multiply(jnp.where(d == D - 1, 0, 1).astype(dtype))
            f = split_dim(f, fe[:-2], fe[2:], v[:, 2], dtype(inv_dx[2]), dt, 0)
            return (f[None],)

        # ---- blocked fused Pallas step (ops/vlasov_kernel.py): all three
        # dimension splits in one HBM pass, bit-identical to `body`.  An
        # optimization layered over the always-built XLA step: a Mosaic
        # rejection at first call disables it for the instance (the
        # flat-AMR / fused-GoL fallback pattern)
        fused_block = 0
        from ..ops.dense_advection import have_pallas, pallas_available
        from ..ops.vlasov_kernel import (
            make_vlasov_step_blocked,
            pick_vlasov_block,
        )

        interpret = self.use_pallas == "interpret"
        nzl, ny, nx, B = info.nz_local, info.ny, info.nx, self.B
        blk = pick_vlasov_block(nzl, ny, nx, B)
        body_fast = None
        if (
            self.use_pallas
            and have_pallas()
            and np.dtype(dtype) == np.float32
            and blk
            and (interpret or pallas_available(np.float32))
        ):
            fused_block = blk
            kern = make_vlasov_step_blocked(
                nzl, ny, nx, B, inv_dx, periodic, block=blk,
                interpret=interpret,
            )
            vb = jnp.asarray(self.v_bins, jnp.float32)
            vxb = vb[:, 0].reshape(1, 1, 1, B)
            vyb = vb[:, 1].reshape(1, 1, 1, B)
            vzb = vb[:, 2].reshape(1, 1, 1, B)

            def body_fast(f, dt):
                f = f[0]
                lo, hi = extend.planes(f)
                if not periodic[2]:
                    # open z: the wrap-received device-edge planes are
                    # vacuum — below device 0, above device D-1
                    d = jax.lax.axis_index(SHARD_AXIS)
                    lo = lo * jnp.where(d == 0, 0, 1).astype(dtype)
                    hi = hi * jnp.where(d == D - 1, 0, 1).astype(dtype)
                return (kern(f, lo, hi, vxb, vyb, vzb, dt)[None],)

        def make_pair(b):
            fn = shard_map(
                b,
                mesh=mesh,
                in_specs=(data_spec, P()),
                out_specs=(data_spec,),
                check_vma=False,
            )

            @jax.jit
            def step(state, dt):
                (f,) = fn(state["f"], jnp.asarray(dt, dtype))
                return {"f": f}

            @jax.jit
            def run(state, steps, dt):
                dt = jnp.asarray(dt, dtype)
                return jax.lax.fori_loop(
                    0, steps, lambda i, st: step(st, dt), state
                )

            return step, run

        step_xla, run_xla = make_pair(body)
        if body_fast is not None:
            step_fast, run_fast = make_pair(body_fast)
        else:
            step_fast, run_fast = step_xla, run_xla
        return {
            "fused_block": fused_block,
            "step_xla": step_xla,
            "run_xla": run_xla,
            "step": step_fast,
            "run": run_fast,
        }

    def _disable_fused(self):
        self._fused_block = 0
        self._step, self._run = self._step_xla, self._run_xla

    # --------------------------------------------------- general (AMR)

    def _build_general_step(self):
        """Row-layout Vlasov over the gather tables — the reference's
        actual Vlasiator shape: an AMR spatial grid with one f(v) block
        per leaf.  Per-face semantics mirror the advection workload's
        (``solve.hpp:129-260`` via the shared face tables) with the
        bin's CONSTANT velocity as the face velocity (spatially constant
        fields make the reference's length-weighted interpolation the
        identity), applied to every bin at once on the ``[D, R, B]``
        payload."""
        from ..parallel.stencil import (
            StencilTables,
            gather_neighbors,
            ordered_sum,
        )
        from .advection import build_face_tables

        from ..parallel.mesh import put_table

        grid = self.grid
        dtype = self.dtype
        self.tables = StencilTables(grid, None, with_geometry=True)
        self._exchange = grid.halo(None)
        host_face, dev = build_face_tables(grid, None, self.tables, dtype)
        t = self.tables.tree()

        # open-boundary face areas per cell per axis/side: the dense
        # path's vacuum-inflow/free-outflow closure (zero incoming, full
        # upwind outgoing) — a boundary face emits no hood entry, so its
        # outflow must be priced explicitly or open boundaries silently
        # degrade to zero-flux walls
        epoch = grid.epoch
        mapping = epoch.mapping
        leaves = epoch.leaves
        cells = leaves.cells
        idxs = mapping.get_indices(cells).astype(np.int64)
        clen = mapping.get_cell_length_in_indices(cells).astype(np.int64)
        lengths = np.asarray(grid.geometry.get_length(cells), np.float64)
        extent = (np.asarray(mapping.length, np.int64)
                  << mapping.max_refinement_level)
        D, R = epoch.n_devices, epoch.R
        bnd_pos = np.zeros((3, D, R))
        bnd_neg = np.zeros((3, D, R))
        devs, rows = epoch.global_rows(np.arange(len(cells)))
        for d3 in range(3):
            if grid.topology.is_periodic(d3):
                continue
            area = lengths[:, (d3 + 1) % 3] * lengths[:, (d3 + 2) % 3]
            hi = (idxs[:, d3] + clen) == extent[d3]
            lo = idxs[:, d3] == 0
            bnd_pos[d3][devs, rows] = np.where(hi, area, 0.0)
            bnd_neg[d3][devs, rows] = np.where(lo, area, 0.0)
        has_open = bool(bnd_pos.any() or bnd_neg.any())
        # one (D, R) table per axis/side: put_table shards the leading
        # (device) axis
        bnd_pos_dev = tuple(put_table(bnd_pos[d3], grid.mesh, dtype)
                            for d3 in range(3))
        bnd_neg_dev = tuple(put_table(bnd_neg[d3], grid.mesh, dtype)
                            for d3 in range(3))

        from ..parallel.exec_cache import traced_jit

        ex = self._exchange
        ex_body = ex.raw_body
        rings = tuple(ex.ring_send) + tuple(ex.ring_recv)

        def build():
            def step(rings, t, dev, vbT, bnd_pos_dev, bnd_neg_dev,
                     state, dt):
                state = {**state, **ex_body(*rings, {"f": state["f"]})}
                f = state["f"]                            # [D, R, B]
                f_n = gather_neighbors(f, t["nbr_rows"])  # [D, R, K, B]
                sgn = jnp.sign(dev["face_dir"]).astype(f.dtype)[..., None]
                ai = dev["axis_idx"].astype(jnp.int32)    # [D, R, K]
                v_face = vbT[ai]                          # [D, R, K, B]
                f_c = f[:, :, None, :]
                up_pos = jnp.where(v_face >= 0, f_c, f_n)
                up_neg = jnp.where(v_face >= 0, f_n, f_c)
                upwind = jnp.where(sgn > 0, up_pos, up_neg)
                face_flux = (upwind * (dt * v_face)
                             * dev["min_area"][..., None])
                contrib = jnp.where(
                    (dev["face_dir"] != 0)[..., None], -sgn * face_flux,
                    0.0,
                )
                total = ordered_sum(contrib, axis=-2)
                if has_open:
                    # outgoing-only boundary faces (incoming is vacuum)
                    rate = sum(
                        bnd_pos_dev[d3][..., None]
                        * jnp.maximum(vbT[d3], 0)
                        + bnd_neg_dev[d3][..., None]
                        * jnp.maximum(-vbT[d3], 0)
                        for d3 in range(3)
                    )
                    total = total - dt * f * rate
                flux = total * dev["inv_volume"][..., None]
                local = t["local_mask"][..., None]
                return {**state, "f": jnp.where(local, f + flux, f)}

            step_k = traced_jit("vlasov.step", step)

            def run(rings, t, dev, vbT, bnd_pos_dev, bnd_neg_dev,
                    state, steps, dt):
                dt_ = jnp.asarray(dt, dtype)
                return jax.lax.fori_loop(
                    0, steps,
                    lambda i, st: step_k(rings, t, dev, vbT, bnd_pos_dev,
                                         bnd_neg_dev, st, dt_),
                    state,
                )

            # state is positional arg 6 of run; donation joins the cache
            # key below so flipping DCCRG_RUN_DONATE re-keys, not re-uses
            return step_k, traced_jit(
                "vlasov.run", run,
                donate_argnums=(6,) if donate else (),
            )

        from ..parallel.exec_cache import (
            record_run_donation,
            run_donate_enabled,
        )

        donate = run_donate_enabled()
        step_fn, run_fn = self.grid.exec_cache.get(
            ("vlasov.step", ex.structure_key, str(np.dtype(dtype)),
             has_open, donate), build
        )
        vbT = jnp.asarray(self.v_bins.T, dtype)
        args = (rings, t, dev, vbT, bnd_pos_dev, bnd_neg_dev)
        self._has_open = has_open
        self._gen_fn, self._gen_args = step_fn, args
        self._step = self._step_xla = (
            lambda state, dt: step_fn(*args, state, dt)
        )
        if donate:
            def run_donated(state, steps, dt):
                probe = state["f"]
                out = run_fn(*args, state, steps, dt)
                record_run_donation("vlasov", probe)
                return out

            self._run = self._run_xla = run_donated
        else:
            self._run = self._run_xla = (
                lambda state, steps, dt: run_fn(*args, state, steps, dt)
            )
        if self.overlap:
            # the eager kernels above stay on _step_xla/_run_xla (the
            # in-process oracle); step()/run() take the fused split form
            self._build_split_general(host_face, bnd_pos, bnd_neg,
                                      has_open)

    def _build_split_general(self, host_face, bnd_pos, bnd_neg, has_open):
        """Fused split-phase step on the row layout (ISSUE 7): halo
        start → interior bins (compacted inner rows, no data dependence
        on the in-flight f blocks) → ghost merge → boundary bins.  The
        flux math is the eager general step's verbatim, restricted per
        row set — see Advection._build_split_step for the bit-identity
        argument (invalid slots masked by ``face_dir == 0``)."""
        from jax.sharding import PartitionSpec as P

        from ..parallel.exec_cache import traced_jit
        from ..parallel.halo import HaloExchange
        from ..parallel.stencil import ordered_sum
        from ..utils.compat import shard_map
        from .advection import _table_specs, build_split_tables

        grid = self.grid
        dtype = self.dtype
        extra = {}
        for d3 in range(3):
            extra[f"bnd_pos{d3}"] = bnd_pos[d3]
            extra[f"bnd_neg{d3}"] = bnd_neg[d3]
        inner, outer, local = build_split_tables(
            grid, None, host_face, dtype, extra=extra
        )
        ex = self._exchange
        ring_start = ex.make_ring_start()
        ks = tuple(ex.ring_ks)
        mesh = grid.mesh
        rings = tuple(ex.ring_send) + tuple(ex.ring_recv)

        def build():
            nk = len(ks)
            data_spec = P(SHARD_AXIS)
            idx_spec = P(SHARD_AXIS, None)

            def side_update(f, t, vbT, dt):
                rows = t["rows"]
                f_c = f[rows]                               # [W, B]
                f_n = f[t["nbr_rows"]]                      # [W, K, B]
                sgn = jnp.sign(t["face_dir"]).astype(f.dtype)[..., None]
                ai = t["axis_idx"].astype(jnp.int32)
                v_face = vbT[ai]                            # [W, K, B]
                fc = f_c[:, None, :]
                up_pos = jnp.where(v_face >= 0, fc, f_n)
                up_neg = jnp.where(v_face >= 0, f_n, fc)
                upwind = jnp.where(sgn > 0, up_pos, up_neg)
                face_flux = (upwind * (dt * v_face)
                             * t["min_area"][..., None])
                contrib = jnp.where(
                    (t["face_dir"] != 0)[..., None], -sgn * face_flux,
                    0.0,
                )
                total = ordered_sum(contrib, axis=-2)
                if has_open:
                    rate = sum(
                        t[f"bnd_pos{d3}"][..., None]
                        * jnp.maximum(vbT[d3], 0)
                        + t[f"bnd_neg{d3}"][..., None]
                        * jnp.maximum(-vbT[d3], 0)
                        for d3 in range(3)
                    )
                    total = total - dt * f_c * rate
                return f_c + total * t["inv_volume"][..., None]

            def body(*args):
                sends = [a[0] for a in args[:nk]]
                recvs = [a[0] for a in args[nk:2 * nk]]
                ti, to, local, vbT, f, dt = args[2 * nk:]
                sub = lambda t: {k: v[0] for k, v in t.items()}
                ti, to = sub(ti), sub(to)
                fb = f[0]
                payloads = ring_start(fb, sends)
                new_i = side_update(fb, ti, vbT, dt)
                f2 = HaloExchange.ring_finish(fb, recvs, payloads)
                new_o = side_update(f2, to, vbT, dt)
                out = f2.at[ti["rows"]].set(new_i).at[to["rows"]].set(new_o)
                out = jnp.where(local[0][..., None], out, f2)
                return out[None]

            fn = shard_map(
                body,
                mesh=mesh,
                in_specs=(idx_spec,) * (2 * nk)
                + (_table_specs(inner), _table_specs(outer), idx_spec,
                   P())
                + (data_spec, P()),
                out_specs=data_spec,
                check_vma=False,
            )

            def step(rings, ti, to, local, vbT, state, dt):
                return {**state, "f": fn(*rings, ti, to, local, vbT,
                                         state["f"], dt)}

            step_k = traced_jit("vlasov.split_step", step)

            def run(rings, ti, to, local, vbT, state, steps, dt):
                dt_ = jnp.asarray(dt, dtype)
                return jax.lax.fori_loop(
                    0, steps,
                    lambda i, st: step_k(rings, ti, to, local, vbT, st,
                                         dt_),
                    state,
                )

            # state is positional arg 5 of run (see _build_general_step)
            return step_k, traced_jit(
                "vlasov.split_run", run,
                donate_argnums=(5,) if donate else (),
            )

        from ..parallel.exec_cache import (
            record_run_donation,
            run_donate_enabled,
        )

        donate = run_donate_enabled()
        step_fn, run_fn = self.grid.exec_cache.get(
            ("vlasov.split_step", ex.structure_key, str(np.dtype(dtype)),
             has_open, donate), build
        )
        vbT = jnp.asarray(self.v_bins.T, dtype)
        args = (rings, inner, outer, local, vbT)
        self._split_fn_k, self._split_args = step_fn, args
        self._step = lambda state, dt: step_fn(*args, state, dt)
        if donate:
            def run_donated(state, steps, dt):
                probe = state["f"]
                out = run_fn(*args, state, steps, dt)
                record_run_donation("vlasov", probe)
                return out

            self._run = run_donated
        else:
            self._run = lambda state, steps, dt: run_fn(*args, state,
                                                        steps, dt)

    # ------------------------------------------------------------ user API

    def initialize_state(self, thermal_v: float = 0.35):
        info = self.info
        grid = self.grid
        cells = grid.get_cells()
        centers = grid.geometry.get_center(cells)
        # spatial density hump (advection workload's cosine bump in 3-D)
        r = np.minimum(
            np.sqrt(((centers - 0.5) ** 2).sum(axis=1)), 0.25
        ) / 0.25
        rho = 0.25 * (1 + np.cos(np.pi * r)) + 0.01
        maxwell = np.exp(-((self.v_bins**2).sum(axis=1)) / (2 * thermal_v**2))
        maxwell /= maxwell.sum()
        f = rho[:, None] * maxwell[None, :]

        if info is None:
            # general row layout: one [B] block per leaf row
            state = grid.new_state(self.spec())
            state = grid.set_cell_data(state, "f", cells, f)
            return grid.update_copies_of_remote_neighbors(state)

        shape = (info.n_devices, info.nz_local, info.ny, info.nx, self.B)
        host = np.zeros(shape, self.dtype)
        lin = (cells - np.uint64(1)).astype(np.int64)
        x = lin % info.nx
        y = (lin // info.nx) % info.ny
        z = lin // (info.nx * info.ny)
        host[z // info.nz_local, z % info.nz_local, y, x] = f
        return {
            "f": jax.device_put(jnp.asarray(host), shard_spec(self.grid.mesh, 5))
        }

    def step(self, state, dt):
        if self._fused_block:
            return fallback_call(
                "fused Vlasov kernel", self._step, self._step_xla,
                self._disable_fused, state, dt,
            )
        return self._step(state, dt)

    def _wide_spec(self):
        """Exchange-amortized step split (ISSUE 14; see
        ``Advection._wide_spec`` — same face-relevance argument, applied
        per velocity bin).  The open-boundary face areas are scattered to
        EVERY replica row (``wide_halo.scatter_rows``), since interior
        steps update live ghost rows too and the owner-rows-only scatter
        of ``_build_general_step`` would silently zero their outflow."""
        from ..parallel.exec_cache import WideStepSpec, traced_jit
        from ..parallel.mesh import put_table
        from ..parallel.stencil import gather_neighbors, ordered_sum
        from ..parallel.wide_halo import (
            get_wide_plan,
            scatter_rows,
            wide_enabled,
        )
        from .advection import build_face_tables

        if not wide_enabled() or self.info is not None:
            return None
        cached = getattr(self, "_wide_cached", None)
        if cached is not None and cached[0] is self.grid.epoch:
            return cached[1]
        grid = self.grid
        plan = get_wide_plan(grid, None, relevance="face")
        spec = None
        if plan.budget >= 2:
            dtype = self.dtype
            wex = grid.halo(None)
            wex_body = wex.raw_body
            wrings = tuple(wex.ring_send) + tuple(wex.ring_recv)
            mesh = grid.mesh
            _, wdev = build_face_tables(
                grid, None, self.tables, dtype,
                hood_arrays=(plan.nbr_offset, plan.nbr_len,
                             plan.nbr_rows, plan.nbr_valid),
            )
            wt = dict(wdev)
            wt["nbr_rows"] = put_table(plan.nbr_rows, mesh)
            wt["steps_ok"] = put_table(plan.steps_ok, mesh)

            epoch = grid.epoch
            mapping = epoch.mapping
            cells = epoch.leaves.cells
            idxs = mapping.get_indices(cells).astype(np.int64)
            clen = mapping.get_cell_length_in_indices(cells)
            clen = clen.astype(np.int64)
            lengths = np.asarray(
                grid.geometry.get_length(cells), np.float64
            )
            extent = (np.asarray(mapping.length, np.int64)
                      << mapping.max_refinement_level)
            has_open = self._has_open
            for d3 in range(3):
                pos_leaf = np.zeros(len(cells))
                neg_leaf = np.zeros(len(cells))
                if not grid.topology.is_periodic(d3):
                    area = (lengths[:, (d3 + 1) % 3]
                            * lengths[:, (d3 + 2) % 3])
                    hi = (idxs[:, d3] + clen) == extent[d3]
                    pos_leaf = np.where(hi, area, 0.0)
                    neg_leaf = np.where(idxs[:, d3] == 0, area, 0.0)
                wt[f"bnd_pos{d3}"] = put_table(
                    scatter_rows(epoch, pos_leaf), mesh, dtype
                )
                wt[f"bnd_neg{d3}"] = put_table(
                    scatter_rows(epoch, neg_leaf), mesh, dtype
                )

            def build():
                def interior(wt, vbT, state, dt, j):
                    f = state["f"]                            # [D, R, B]
                    f_n = gather_neighbors(f, wt["nbr_rows"])
                    sgn = jnp.sign(wt["face_dir"]).astype(
                        f.dtype
                    )[..., None]
                    ai = wt["axis_idx"].astype(jnp.int32)
                    v_face = vbT[ai]
                    f_c = f[:, :, None, :]
                    up_pos = jnp.where(v_face >= 0, f_c, f_n)
                    up_neg = jnp.where(v_face >= 0, f_n, f_c)
                    upwind = jnp.where(sgn > 0, up_pos, up_neg)
                    face_flux = (upwind * (dt * v_face)
                                 * wt["min_area"][..., None])
                    contrib = jnp.where(
                        (wt["face_dir"] != 0)[..., None],
                        -sgn * face_flux, 0.0,
                    )
                    total = ordered_sum(contrib, axis=-2)
                    if has_open:
                        rate = sum(
                            wt[f"bnd_pos{d3}"][..., None]
                            * jnp.maximum(vbT[d3], 0)
                            + wt[f"bnd_neg{d3}"][..., None]
                            * jnp.maximum(-vbT[d3], 0)
                            for d3 in range(3)
                        )
                        total = total - dt * f * rate
                    flux = total * wt["inv_volume"][..., None]
                    live = (wt["steps_ok"] > j)[..., None]
                    return {**state, "f": jnp.where(live, f + flux, f)}

                return traced_jit("vlasov.wide_step", interior)

            fn = self.grid.exec_cache.get(
                ("vlasov.wide_step", wex.structure_key,
                 str(np.dtype(dtype)), has_open, self.nv), build
            )
            vbT = jnp.asarray(self.v_bins.T, dtype)
            spec = WideStepSpec(
                exchange=lambda args, wargs, state: {
                    **state, **wex_body(*wargs[0], {"f": state["f"]})
                },
                interior=lambda args, wargs, state, dt, j: fn(
                    wargs[1], wargs[2], state, dt, j
                ),
                budget=plan.budget,
                args=(wrings, wt, vbT),
                local_mask=plan.local_mask,
            )
        self._wide_cached = (self.grid.epoch, spec)
        return spec

    def batch_step_spec(self):
        """Cohort-batchable step entry point (ISSUE 9; see
        ``Advection.batch_step_spec``).  ``nv`` rides the kernel key:
        two cohorts with different velocity-space resolutions compile
        different member programs even at one spatial signature."""
        from ..parallel.exec_cache import (
            BatchStepSpec,
            default_steps_per_dispatch,
        )

        k = default_steps_per_dispatch()
        dtype = np.dtype(self.dtype)
        if self.info is not None:
            step = self._step
            return BatchStepSpec(
                kind="vlasov.dense", kernel_key=self._dense_key,
                call=lambda args, state, dt: step(state, dt),
                args=(), dt_dtype=dtype, steps_per_dispatch=k,
            )
        ex = self._exchange
        wide = self._wide_spec()
        if self.overlap:
            fn = self._split_fn_k
            return BatchStepSpec(
                kind="vlasov.split",
                kernel_key=("vlasov.split_step", ex.structure_key,
                            str(dtype), self._has_open, self.nv),
                call=lambda args, state, dt: fn(*args, state, dt),
                args=self._split_args, dt_dtype=dtype,
                steps_per_dispatch=k, wide=wide,
            )
        fn = self._gen_fn
        return BatchStepSpec(
            kind="vlasov",
            kernel_key=("vlasov.step", ex.structure_key, str(dtype),
                        self._has_open, self.nv),
            call=lambda args, state, dt: fn(*args, state, dt),
            args=self._gen_args, dt_dtype=dtype, steps_per_dispatch=k,
            wide=wide,
        )

    def _record_run(self, path: str, steps, state) -> None:
        """Post-run reconciliation (obs.fused): the device-loop runs keep
        their ghost traffic inside jit.  Dense layout: each step's slab
        ring ships two [ny, nx, B] planes per device (none on a single
        device, where the wrap is local); general layout: the full-f
        halo schedule."""
        from ..obs import fused

        if not self.grid.telemetry.enabled:
            return
        try:
            if self.info is not None:
                D = self.grid.n_devices
                itemsize = np.dtype(self.dtype).itemsize
                bps = (
                    D * 2 * self.info.ny * self.info.nx * self.B * itemsize
                    if D > 1 else 0
                )
            else:
                bps = self.grid.halo(None).bytes_moved({"f": state["f"]})
        except Exception:  # noqa: BLE001 — telemetry must never raise
            bps = 0
        fused.record_run("vlasov", path, steps, bps)

    def run(self, state, steps: int, dt):
        if self._fused_block:
            self._record_run("fused", steps, state)
            return fallback_call(
                "fused Vlasov kernel", self._run, self._run_xla,
                self._disable_fused, state, steps, dt,
            )
        self._record_run(
            "xla" if self.info is not None
            else ("split" if self.overlap else "general"),
            steps, state,
        )
        return self._run(state, steps, dt)

    def max_time_step(self) -> float:
        if self.info is None:
            # the general path's update is UNSPLIT: all three dimensions'
            # donor-cell fluxes accumulate in one step, so the stability
            # bound is dt <= 1 / max_cells sum_d |v|max_d / len_d — up
            # to 3x tighter than the per-dimension bound the split dense
            # update obeys
            lengths = np.asarray(
                self.grid.geometry.get_length(self.grid.get_cells()),
                np.float64,
            )
            vmax_d = np.abs(self.v_bins).max(axis=0)       # (3,)
            courant = (vmax_d / np.maximum(lengths, 1e-300)).sum(axis=1)
            return float(1.0 / max(courant.max(), 1e-30))
        l0 = self.grid.geometry.get_level_0_cell_length()
        vmax = np.abs(self.v_bins).max()
        return float(l0.min() / max(vmax, 1e-30))

    def density(self, state) -> np.ndarray:
        """Velocity-space integral per spatial cell: [D, nzl, ny, nx]
        on the dense layout, [D, R] rows on the general layout."""
        return fetch(state["f"], dtype=np.float64).sum(axis=-1)

    def total_mass(self, state) -> float:
        if self.info is None:
            grid = self.grid
            cells = np.sort(grid.leaves.cells)
            rho = np.asarray(
                grid.get_cell_data(state, "f", cells), np.float64
            ).sum(axis=-1)
            vol = np.prod(grid.geometry.get_length(cells), axis=-1)
            return float((rho * vol).sum())
        l0 = self.grid.geometry.get_level_0_cell_length()
        return float(self.density(state).sum() * np.prod(l0))
