"""Multi-step advection over the boxed per-level AMR layout
(``parallel/boxed.py``) — single- OR multi-device, one unified dense pass
per level per step.

Layout recap (see ``parallel/boxed.py``): every refinement level's leaves
live in a dense box — the tight leaf bounding box on one device, or (multi-
device) the full domain in z and the bounding box in x/y, z-slab
partitioned over the device mesh with one equal slab per device.  Each
device's slab is extended by a one-voxel ring:

* z ring: the neighbor devices' edge planes via a circular
  ``lax.ppermute`` (the circular ring IS the periodic z wrap; with one
  device it degenerates to a local wrap — exact when the box covers a
  periodic z axis, masked out otherwise);
* x/y ring: a local pad — wrap where the box covers a periodic axis, zero
  otherwise.

Every ring voxel carries ``val = use_rho ? rho : upsampled-coarse``; a
single per-axis upwind flux pass over ``val`` with combined static weights
prices same-level AND coarse|fine faces together (the 2:1 face velocity
``(2*v_fine + v_coarse)/3`` — the reference interpolation
``(cl*v_nbr + nl*v_cell)/(cl+nl)`` with ``nl == 2*cl``, solve.hpp:168-175 —
is baked into the weight).  Fine cells read their own deltas directly; the
deltas accumulated on NON-leaf voxels are exactly the coarse receivers'
mass fluxes, recovered by a parity-aligned 2x sum-pool per pair.

The z axis runs in one of two statically chosen modes (the step body is a
single code path; only mask construction, the upsample window, and the
pooled routing differ):

* **local** (one device): z is just another axis — tight extent, cross
  faces register on ring rows where they fall off the box, and pooled
  fluxes route by contiguous segments with modulo wrap, exactly like x/y;
* **slab** (multi-device): full-domain extent, cut at equal per-device
  slabs.  z-wrap mask images register at their true modulo coordinate, so
  every device prices every face REGISTERED in its padded slab — cut and
  periodic-seam faces are priced by BOTH adjacent devices from
  bit-identical inputs (shard_map compiles one program for all devices).
  A device keeps only deltas landing on its interior rows and only pooled
  rows mapping into its own coarse slab interior; the boundary pooled
  rows are exact duplicates of a z-neighbor's local sums and are dropped.
  Each face is thus delivered exactly once per receiving cell with zero
  cross-device flux traffic — the per-step collectives are just 2
  ppermuted rho planes per level, the same wire pattern as the uniform
  dense path (``parallel/dense.py``), generalized per level.

Velocities are loop-invariant inside a run, so all weights and upwind
selections are computed once at run start; the loop body touches only
density.  Produces the same update as the general gather path
(solve.hpp:129-260 semantics) with a different — but fixed —
floating-point association order.
"""
from __future__ import annotations

import jax
from ..utils.compat import shard_map
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import SHARD_AXIS, put_table, shard_spec

__all__ = ["build_boxed_run"]


def _clip(v, lo, hi):
    return int(min(max(v, lo), hi))


def _runs(idx):
    """Split an index vector into maximal stride-1 runs -> [(start, stop)]
    half-open slices of the source array."""
    cuts = np.flatnonzero(np.diff(idx) != 1) + 1
    return [(int(p[0]), int(p[0]) + len(p)) for p in np.split(idx, cuts)]


def _route_segments(g, gm, n_valid):
    """Contiguous segments of pooled rows mapping to contiguous target
    coordinates under modulo wrap: the main in-domain block plus one
    single-row segment per wrapped edge row (a box touching but not
    covering a periodic axis wraps to the far side of the domain); either
    way each segment gets its own slice-add, so no pooled flux is ever
    dropped."""
    inside = (gm >= 0) & (gm < n_valid)
    main = (g >= 0) & (g < n_valid)
    segs = []
    if main.any():
        i0 = int(np.argmax(main))
        i1 = int(len(g) - np.argmax(main[::-1]))
        segs.append((i0, i1, int(g[i0])))
    for i in np.flatnonzero(inside & ~main):
        segs.append((int(i), int(i) + 1, int(gm[i])))
    return segs


def build_boxed_run(adv, layout):
    """Build the jitted ``run(state, steps, dt) -> state`` for ``adv`` (an
    ``Advection`` model) over ``layout`` (a ``BoxedLayout``)."""
    dtype = adv.dtype
    grid = adv.grid
    mapping = grid.mapping
    topology = grid.topology
    mesh = grid.mesh
    D = layout.n_devices
    slab_z = D > 1
    scratch = grid.epoch.R - 1
    periodic = [topology.is_periodic(d) for d in range(3)]
    boxes = sorted(layout.boxes.values(), key=lambda b: b.level)
    lvl_index = {b.level: i for i, b in enumerate(boxes)}
    pair_of_fine = {pr.fine_level: pr for pr in layout.pairs}
    L = len(boxes)

    # ---------------------------------------------- per-level static tables
    consts = []      # python-side metadata per level
    statics = []     # device-stacked arrays per level (shipped via shard_map)
    for b in boxes:
        lvl = b.level
        lo = b.lo.astype(np.int64)                  # (3,) x,y,z
        bz, by, bx = b.shape
        nzl = bz // D
        dims = np.array([bx, by, bz])
        n_dom = np.array(mapping.length) << lvl     # domain extent, x,y,z
        covers = [
            bool(periodic[d] and lo[d] == 0 and dims[d] == n_dom[d])
            for d in range(3)
        ]
        # how mask ring rows are filled along z: slab mode needs the
        # circularly consistent wrap whenever z is periodic (the device
        # ring); local mode wraps only when the box covers the axis
        z_mask_wrap = periodic[2] if slab_z else covers[2]

        def pad3(arr, xy_wrap, fill=False, z_wrap=z_mask_wrap):
            """Ring-pad (bz, by, bx) -> (bz+2, by+2, bx+2)."""
            out = arr
            for a, cov in ((0, z_wrap), (1, xy_wrap and covers[1]),
                           (2, xy_wrap and covers[0])):
                pw = [(0, 0)] * 3
                pw[a] = (1, 1)
                if cov:
                    out = np.pad(out, pw, mode="wrap")
                else:
                    out = np.pad(out, pw, mode="constant", constant_values=fill)
            return out

        use_rho = pad3(b.leaf_mask, xy_wrap=True)
        m_same = np.stack([pad3(b.face_valid[d], xy_wrap=True)
                           for d in range(3)])
        # cross-face masks: fine-low (mask_plus at the fine voxel) and
        # fine-high (mask_minus registered at the coarse voxel p - e_d).
        # Shifts falling off the box either fold to their true modulo
        # coordinate (slab z: required so the device owning the periodic
        # seam's coarse side prices the wrap face locally) or stay on the
        # ring row and are delivered by the pooled wrap segments (local
        # mode and x/y).
        m_lowf_i = np.zeros((3, bz, by, bx), dtype=bool)
        m_highf_i = np.zeros((3, bz, by, bx), dtype=bool)
        edge_planes = {}                            # d -> ring-row-0 plane
        pr = pair_of_fine.get(lvl)
        if pr is not None:
            for d in range(3):
                m_lowf_i[d] = pr.mask_plus[d]
                ax = 2 - d
                mm = pr.mask_minus[d]
                src = [slice(None)] * 3
                dst = [slice(None)] * 3
                src[ax] = slice(1, None)
                dst[ax] = slice(0, -1)
                m_highf_i[d][tuple(dst)] = mm[tuple(src)]
                edge_sl = [slice(None)] * 3
                edge_sl[ax] = 0
                edge = mm[tuple(edge_sl)]
                if not edge.any():
                    continue
                if d == 2 and slab_z:
                    # register at the true coordinate bz-1
                    assert periodic[2], "cross face below a non-periodic floor"
                    m_highf_i[d][-1] |= edge
                else:
                    edge_planes[d] = edge
        # Cross-face mask ring padding is MODE-dependent along z:
        # * slab mode wrap-pads — the global rings must be circularly
        #   consistent so each device's ring rows carry the seam faces it
        #   must price (the re-registered fine-below-the-floor faces at
        #   interior bz-1 reach the wrap-adjacent device through its ring
        #   row; same-level seam faces ride m_same's wrap the same way);
        # * local mode constant-pads — its box-edge faces are placed
        #   explicitly on ring row 0 below, and a wrap pad would copy
        #   interior cross-face registrations onto the opposite ring row
        #   as spurious faces, which the pooled wrap segments then deliver
        #   as phantom fluxes into the far-side coarse cells.
        cross_z_wrap = z_mask_wrap if slab_z else False
        m_lowf = np.stack([
            pad3(m_lowf_i[d], xy_wrap=False, z_wrap=cross_z_wrap)
            for d in range(3)
        ])
        m_highf = np.stack([
            pad3(m_highf_i[d], xy_wrap=False, z_wrap=cross_z_wrap)
            for d in range(3)
        ])
        for d, edge in edge_planes.items():
            ax = 2 - d
            sl = [slice(1, 1 + bz), slice(1, 1 + by), slice(1, 1 + bx)]
            sl[ax] = 0
            m_highf[d][tuple(sl)] = edge
        # no face may pair the last ring voxel with the (rolled) first;
        # x/y here, the z edge below (per slab, since every slab's last
        # padded row pairs with a nonexistent row under the rolled pass)
        for m in (m_same, m_lowf, m_highf):
            for d in range(2):
                ax = 2 - d
                sl = [slice(None)] * 3
                sl[ax] = slice(-1, None)
                m[d][tuple(sl)] = False

        # z-slab stacking: device k's padded rows are [k*nzl, k*nzl+nzl+2)
        # of the global padded array (its ring rows are the neighbors'
        # interior rows / the circularly consistent global ring rows);
        # one device: the whole padded box
        def slab_pad(arr_g):                        # padded global -> [D, ...]
            return np.stack([arr_g[..., k * nzl:k * nzl + nzl + 2, :, :]
                             for k in range(D)])

        def slab_int(arr_g):                        # interior global -> [D, ...]
            return np.stack([arr_g[..., k * nzl:(k + 1) * nzl, :, :]
                             for k in range(D)])

        m_same_s = slab_pad(m_same)                 # [D, 3, nzl+2, by+2, bx+2]
        m_lowf_s = slab_pad(m_lowf)
        m_highf_s = slab_pad(m_highf)
        use_rho_s = slab_pad(use_rho)
        for m in (m_same_s, m_lowf_s, m_highf_s):
            m[:, :, -1] = False
        any_face_s = m_same_s | m_lowf_s | m_highf_s

        rows_s = slab_int(b.rows.reshape(bz, by, bx))
        leaf_s = slab_int(b.leaf_mask)

        # final scatter tables: per device, flat slab positions of its
        # leaves and their local epoch rows (padded to a common length;
        # pads write into the scratch row)
        flats, rowss = [], []
        for k in range(D):
            fl = np.flatnonzero(leaf_s[k].ravel())
            flats.append(fl)
            rowss.append(rows_s[k].ravel()[fl])
        M = max((len(f) for f in flats), default=0) or 1
        leaf_flat_s = np.zeros((D, M), dtype=np.int32)
        leaf_rows_s = np.full((D, M), scratch, dtype=np.int32)
        for k in range(D):
            leaf_flat_s[k, : len(flats[k])] = flats[k]
            leaf_rows_s[k, : len(rowss[k])] = rowss[k]

        area = np.array(
            [
                b.length[1] * b.length[2],
                b.length[0] * b.length[2],
                b.length[0] * b.length[1],
            ]
        )
        consts.append(
            dict(
                covers=covers,
                area=area.astype(dtype),
                inv_vol=dtype(1.0 / float(np.prod(b.length))),
            )
        )
        statics.append(
            dict(
                rows=rows_s.astype(np.int32),
                leaf=leaf_s,
                use_rho=use_rho_s,
                m_same=m_same_s,
                m_lowf=m_lowf_s,
                m_highf=m_highf_s,
                any_face=any_face_s,
                pool_mask=~use_rho_s,
                leaf_flat=leaf_flat_s,
                leaf_rows=leaf_rows_s,
            )
        )

    # ------------------------------------------ per-pair static plumbing
    # Window segments for the coarse->fine upsample and routing segments
    # for the pooled fine->coarse fluxes.  x/y (and local-mode z) go
    # through clip/wrap segment decomposition; slab-mode z needs neither —
    # alignment makes the window the whole ringed coarse slab and the
    # routing an interior crop.
    pconsts = {}
    for pr in layout.pairs:
        fb = layout.boxes[pr.fine_level]
        cb = layout.boxes[pr.coarse_level]
        fi, ci = lvl_index[pr.fine_level], lvl_index[pr.coarse_level]
        lo_f = fb.lo.astype(np.int64)
        lo_c = cb.lo.astype(np.int64)
        bz, by, bx = fb.shape
        dims_f = np.array([bx, by, bz])
        cz, cy, cx = cb.shape
        dims_c = np.array([cx, cy, cz])
        nzl_f = bz // D
        nzc = cz // D
        n_c = np.array(mapping.length) << pr.coarse_level
        clo = (lo_f - 1) >> 1
        chi = ((lo_f + dims_f) >> 1) + 1
        # upsample window: per axis, maximal stride-1 runs — the window
        # becomes a concat of static slices, no gather op anywhere
        # (gathers are the single most expensive lowering on TPU for this
        # access pattern).  Indices are into the z-RINGED coarse slab
        # (z + 1 shift); slab-mode z uses the whole ringed slab.
        win_segs = []
        for d in range(3):
            if d == 2 and slab_z:
                win_segs.append([(0, nzc + 2)])
                continue
            coords = np.arange(clo[d], chi[d])
            if periodic[d]:
                coords = coords % n_c[d]
            idx = np.clip(coords - lo_c[d], 0, dims_c[d] - 1)
            if d == 2:
                idx = idx + 1                       # into the ringed slab
            win_segs.append(_runs(idx))
        off = lo_f - 1 - 2 * clo                    # 0/1 per axis
        off_z = 1 if slab_z else int(off[2])

        def upsample(c_rz, win_segs=win_segs, off=off, off_z=off_z,
                     nzl=nzl_f, shape=(by, bx)):
            """(nzc+2, cy, cx) z-ringed coarse -> (nzl+2, by+2, bx+2)."""
            win = c_rz
            for a in range(3):
                segs = win_segs[2 - a]
                if len(segs) == 1 and segs[0] == (0, win.shape[a]):
                    continue
                parts = [
                    jax.lax.slice_in_dim(win, i0, i1, axis=a)
                    for i0, i1 in segs
                ]
                win = parts[0] if len(parts) == 1 else jnp.concatenate(
                    parts, axis=a
                )
            up = win
            for a in range(3):
                up = jnp.repeat(up, 2, axis=a)
            by_, bx_ = shape
            return up[
                off_z:off_z + nzl + 2,
                off[1]:off[1] + by_ + 2,
                off[0]:off[0] + bx_ + 2,
            ]

        # pooled routing: pad the ringed fine slab to global-even parity,
        # 2x sum-pool, then slice-add per cartesian combination of
        # per-axis segments.  Each segment is (src_start, length,
        # target_start) with clipping against the coarse box already
        # applied; slab-mode z contributes the single interior crop (the
        # boundary pooled rows are dropped — each is an exact duplicate of
        # a z-neighbor device's local sums, or of the wrap image priced by
        # the owning device).
        go = lo_f - 1
        plo_pad = [int(go[d] & 1) for d in range(3)]
        if slab_z:
            plo_pad[2] = 1                          # slab start is even
        psz = [int(dims_f[d]) + 2 + plo_pad[d] for d in range(3)]
        psz[2] = nzl_f + 2 + plo_pad[2]
        phi_pad = [psz[d] % 2 for d in range(3)]
        npool = [(psz[d] + phi_pad[d]) // 2 for d in range(3)]
        cplo = go >> 1
        segments = []                               # per axis: (s0, len, t0)
        for d in range(3):
            if d == 2 and slab_z:
                segments.append([(1, nzc, 0)])
                continue
            g = cplo[d] + np.arange(npool[d])
            gm = g % n_c[d] if periodic[d] else g
            segs = []
            for i0, i1, gt in _route_segments(g, gm, int(n_c[d])):
                t0 = gt - int(lo_c[d])
                c0 = _clip(t0, 0, int(dims_c[d]))
                c1 = _clip(t0 + (i1 - i0), 0, int(dims_c[d]))
                if c1 > c0:
                    segs.append((i0 + c0 - t0, c1 - c0, c0))
            segments.append(segs)

        def pool_route(delta_c_pad, P_src, plo_pad=plo_pad, phi_pad=phi_pad,
                       segments=segments):
            """2x sum-pool the masked ring-grid deltas and add them into the
            coarse level's padded slab delta (wrap images of the same
            coarse row accumulate — they carry different faces'
            fluxes)."""
            Pp = jnp.pad(
                P_src,
                ((plo_pad[2], phi_pad[2]), (plo_pad[1], phi_pad[1]),
                 (plo_pad[0], phi_pad[0])),
            )
            # 2x sum-pool as three strided-slice adds (XLA fuses these into
            # one pass; the 6-D reshape+reduce form does not tile as well)
            Q = Pp
            for a in range(3):
                lo_sl = [slice(None)] * 3
                hi_sl = [slice(None)] * 3
                lo_sl[a] = slice(0, None, 2)
                hi_sl[a] = slice(1, None, 2)
                Q = Q[tuple(lo_sl)] + Q[tuple(hi_sl)]
            for z0, lz, tz in segments[2]:
                for y0, ly, ty in segments[1]:
                    for x0, lx, tx in segments[0]:
                        Ps = Q[z0:z0 + lz, y0:y0 + ly, x0:x0 + lx]
                        delta_c_pad = delta_c_pad.at[
                            1 + tz:1 + tz + lz,
                            1 + ty:1 + ty + ly,
                            1 + tx:1 + tx + lx,
                        ].add(Ps)
            return delta_c_pad

        pconsts[fi] = dict(ci=ci, upsample=upsample, pool_route=pool_route)

    # --------------------------------------------------- the sharded body
    up_perm = [(i, (i + 1) % D) for i in range(D)]
    down_perm = [(i, (i - 1) % D) for i in range(D)]

    def zring(x):
        """(nz_loc, ...) -> (nz_loc+2, ...): neighbor edge planes over the
        circular device ring (one device: local wrap)."""
        top, bot = x[-1:], x[:1]
        if D == 1:
            rb, ra = top, bot
        else:
            rb = jax.lax.ppermute(top, SHARD_AXIS, up_perm)
            ra = jax.lax.ppermute(bot, SHARD_AXIS, down_perm)
        return jnp.concatenate([rb, x, ra], axis=0)

    def pad_xy(x, covers):
        """(nz+2, by, bx) -> (nz+2, by+2, bx+2)."""
        for a, cov in ((1, covers[1]), (2, covers[0])):
            pw = [(0, 0)] * 3
            pw[a] = (1, 1)
            x = jnp.pad(x, pw, mode="wrap" if cov else "constant")
        return x

    def body(rho_b, vx_b, vy_b, vz_b, dt, steps, st):
        rho_flat = rho_b[0]
        v_flat = (vx_b[0], vy_b[0], vz_b[0])
        C = [{k: v[0] for k, v in s.items()} for s in st]  # strip dev axis

        def to_slab(flat, li):
            vals = flat[C[li]["rows"]]
            return jnp.where(C[li]["leaf"], vals, 0)

        rhos = tuple(to_slab(rho_flat, li) for li in range(L))
        vels = [tuple(to_slab(v, li) for v in v_flat) for li in range(L)]

        # static per-level face weights and upwind selections (velocity is
        # loop-invariant; the ring exchanges here run once per run)
        stat = []
        for li, c in enumerate(consts):
            p = pconsts.get(li)
            ups = (
                [p["upsample"](zring(vels[p["ci"]][d])) for d in range(3)]
                if p is not None
                else None
            )
            per_axis = []
            for d in range(3):
                ax = 2 - d
                vv = pad_xy(zring(vels[li][d]), c["covers"])
                if ups is not None:
                    vv = jnp.where(C[li]["use_rho"], vv, ups[d])
                vl, vh = vv, jnp.roll(vv, -1, ax)
                v_face = jnp.where(
                    C[li]["m_same"][d], 0.5 * (vl + vh),
                    jnp.where(
                        C[li]["m_lowf"][d], (2 * vl + vh) / 3,
                        (vl + 2 * vh) / 3,
                    ),
                )
                w = jnp.where(
                    C[li]["any_face"][d], dt * v_face * c["area"][d], 0
                )
                per_axis.append((v_face >= 0, w))
            stat.append(per_axis)

        def step(i, rhos):
            rz = [zring(r) for r in rhos]
            deltas = []
            for li, c in enumerate(consts):
                p = pconsts.get(li)
                val = pad_xy(rz[li], c["covers"])
                if p is not None:
                    val = jnp.where(
                        C[li]["use_rho"], val, p["upsample"](rz[p["ci"]])
                    )
                delta = jnp.zeros_like(val)
                for d in range(3):
                    ax = 2 - d
                    upsel, w = stat[li][d]
                    F = jnp.where(upsel, val, jnp.roll(val, -1, ax)) * w
                    delta = delta + (jnp.roll(F, 1, ax) - F)
                deltas.append(delta)
            # route non-leaf voxel deltas (= coarse receivers' fluxes)
            # fine-to-coarse, finest level first
            for li in range(L - 1, -1, -1):
                p = pconsts.get(li)
                if p is None:
                    continue
                deltas[p["ci"]] = p["pool_route"](
                    deltas[p["ci"]], deltas[li] * C[li]["pool_mask"]
                )
            new = []
            for li, c in enumerate(consts):
                d_in = deltas[li][1:-1, 1:-1, 1:-1]
                new.append(
                    jnp.where(
                        C[li]["leaf"], rhos[li] + d_in * c["inv_vol"], 0
                    )
                )
            return tuple(new)

        rhos = jax.lax.fori_loop(0, steps, step, rhos)
        out = rho_flat
        for li in range(L):
            out = out.at[C[li]["leaf_rows"]].set(
                rhos[li].reshape(-1)[C[li]["leaf_flat"]]
            )
        return out[None]

    statics_dev = [
        {k: put_table(v, mesh) for k, v in s.items()}
        for s in statics
    ]
    st_specs = [
        {k: P(SHARD_AXIS, *([None] * (v.ndim - 1))) for k, v in s.items()}
        for s in statics
    ]
    data_spec = P(SHARD_AXIS)
    sm = shard_map(
        body,
        mesh=mesh,
        in_specs=(data_spec, data_spec, data_spec, data_spec, P(), P(),
                  st_specs),
        out_specs=data_spec,
    )

    # the boxed tables ride into the jit as a RUNTIME argument pytree
    # (not closed over): same-shape boxings share one executable
    @jax.jit
    def run_impl(statics_arg, state, steps, dt):
        dt = jnp.asarray(dt, dtype)
        steps = jnp.asarray(steps, jnp.int32)
        density = sm(
            state["density"], state["vx"], state["vy"], state["vz"],
            dt, steps, statics_arg,
        )
        return {
            **state,
            "density": density,
            "flux": jnp.zeros_like(state["flux"]),
        }

    def run(state, steps, dt):
        return run_impl(statics_dev, state, steps, dt)

    return run
