"""Poisson solver on the (possibly AMR-refined) grid.

Reproduces the discretization and algorithm of the reference's parallel
Poisson solver (``tests/poisson/poisson_solve.hpp``):

* geometric factors per face direction from cell-center distances,
  ``f_side = ±2 / (offset_side * total_offset)`` with missing neighbors
  giving factor 0 (Neumann walls) and the diagonal ``scaling_factor =
  -sum(f)`` (``poisson_solve.hpp:691-822``);
* a finer face neighbor's contribution is divided by 4 — its 4 sub-faces
  share one coarse face (``poisson_solve.hpp:332-336``);
* the biconjugate-gradient iteration of Numerical Recipes 2.7.6 with both
  ``A·p`` and ``Aᵀ·p`` applied matrix-free (``poisson_solve.hpp:251-520``);
* the reference's three cell roles (``poisson_solve.hpp:146-150, 829-965``):
  cells listed in ``solve_cells`` are solved; cells in ``skip_cells`` are
  treated as missing neighbors (factor 0 toward them); remaining cells are
  *boundary* cells whose rhs/solution feed the solver (Dirichlet data) but
  are never updated — boundary-boundary neighbor pairs are dropped.

TPU-native formulation: the per-entry forward and transpose multipliers are
precomputed host-side into ``[D, R, K]`` tables, so each BiCG iteration is
two gathers + ordered reductions and two global dot products, all inside
one jitted ``lax.while_loop`` (a single device dispatch per solve).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.stencil import StencilTables, gather_neighbors, ordered_sum
from ..utils.collectives import fetch

__all__ = ["Poisson"]


class Poisson:
    SPEC = {
        "rhs": ((), np.float64),
        "solution": ((), np.float64),
    }

    #: cell roles, same codes as the reference (poisson_solve.hpp:146-150)
    SOLVE_CELL = 0
    BOUNDARY_CELL = 1
    SKIP_CELL = 2

    def __init__(self, grid, hood_id=None, dtype=None,
                 solve_cells=None, skip_cells=None, allow_flat=True,
                 use_pallas=True, allow_rolled=None):
        #: use_pallas follows the Advection convention: True = compiled
        #: kernels on TPU only; "interpret" = Pallas interpreter
        #: (CI/CPU coverage); False = XLA only
        self.grid = grid
        self.hood_id = hood_id
        # default dtype: f64 where x64 is enabled (the reference solves in
        # doubles), otherwise f32 up front instead of a per-alloc
        # truncation warning
        if dtype is None:
            import jax

            dtype = (np.float64 if jax.config.jax_enable_x64
                     else np.float32)
        self.dtype = dtype
        self.use_pallas = use_pallas
        self.spec = {k: (s, dtype) for k, (s, _) in self.SPEC.items()}
        self.tables = StencilTables(grid, hood_id, with_geometry=True)
        self._exchange = grid.halo(hood_id)
        self._full_solve = solve_cells is None
        self._build_cell_types(solve_cells, skip_cells)
        self._build_factors()
        self._flat_tables = None
        self._flat = self._build_flat() if allow_flat else None
        # rolled static-offset matvec (ops/rolled_gather.py): replaces
        # the [R, K] row gather in the general-path solver when the flat
        # operator does not engage; the raw gather (_apply) remains the
        # operator oracle and the residual() diagnostic.  Default
        # (None): accelerator backends only — XLA CPU's gather is
        # already vectorized (measured 2.1x FASTER than the roll chain
        # on the refined bench config), while the TPU lowering
        # scalarizes it (the 0.13x-vs-CPU showing the decomposition
        # replaces).  Pass True/False to pin either way.
        if allow_rolled is None:
            import jax

            allow_rolled = jax.default_backend() != "cpu"
        self._rolled = (self._build_rolled()
                        if allow_rolled and self._flat is None else None)
        self._solve = self._build_solver()
        self._solve_fast = self._build_fast_solver()

    def _build_flat(self):
        """Dense flat-voxel operator (ops/flat_poisson.py) — engaged when
        the grid qualifies (Cartesian, leaf levels ≤ flat_amr._ML_MAX_VL
        = 4 via the inflated-voxel layout; multi-device when ownership is
        the voxel z-slab partition); the gather tables remain the general
        path and the oracle for deeper refinement."""
        from ..ops.flat_poisson import (
            build_flat_poisson,
            make_flat_poisson_apply,
        )

        t = build_flat_poisson(
            self.grid,
            self._f_pos_leaf,
            self._f_neg_leaf,
            self._scaling_leaf,
            self._cell_type_leaf,
            self.SOLVE_CELL,
            self.SKIP_CELL,
            self.BOUNDARY_CELL,
        )
        if t is None:
            return None
        self._flat_tables = t
        return make_flat_poisson_apply(
            t, jnp.dtype(self.dtype), mesh=self.grid.mesh
        )

    def _build_cell_types(self, solve_cells, skip_cells):
        """Per-leaf role array (reference cache_system_info,
        ``poisson_solve.hpp:829-965``): everything not solved or skipped is
        a boundary cell; solve membership wins over skip."""
        leaves = self.grid.epoch.leaves
        N = len(leaves)
        if solve_cells is None:
            types = np.full(N, self.SOLVE_CELL, dtype=np.int8)
            if skip_cells is not None and len(skip_cells):
                pos = leaves.position(np.asarray(skip_cells, dtype=np.uint64))
                types[pos] = self.SKIP_CELL
        else:
            types = np.full(N, self.BOUNDARY_CELL, dtype=np.int8)
            if skip_cells is not None and len(skip_cells):
                pos = leaves.position(np.asarray(skip_cells, dtype=np.uint64))
                types[pos] = self.SKIP_CELL
            pos = leaves.position(np.asarray(solve_cells, dtype=np.uint64))
            types[pos] = self.SOLVE_CELL
        self._cell_type_leaf = types

    # ---------------------------------------------------------- factors

    def _build_factors(self):
        """Factors are computed over the GLOBAL leaf arrays (so transpose
        multipliers can reference any neighbor's factors, local or ghost)
        and then scattered into the per-device [D, R, K] tables."""
        grid = self.grid
        epoch = grid.epoch
        hood = epoch.hoods[self.hood_id]
        lists = hood.lists
        leaves = epoch.leaves
        N = len(leaves)
        D, R, K = hood.nbr_rows.shape

        counts = np.diff(lists.start)
        src = np.repeat(np.arange(N, dtype=np.int64), counts)
        nbr = lists.nbr_pos
        off = lists.offset                               # (E, 3) index units
        clen_i = grid.mapping.get_cell_length_in_indices(leaves.cells).astype(np.int64)
        nlen_i = clen_i[nbr]
        slen_i = clen_i[src]

        # face classification per entry (solve.hpp:71-123 offset logic)
        overlap = (off < slen_i[:, None]) & (off > -nlen_i[:, None])
        n_overlap = overlap.sum(axis=1)
        direction = np.zeros(len(src), dtype=np.int8)
        for d in range(3):
            direction = np.where(
                (n_overlap == 2) & (off[:, d] == slen_i), d + 1, direction
            )
            direction = np.where(
                (n_overlap == 2) & (off[:, d] == -nlen_i), -(d + 1), direction
            )

        # pairs involving a skip cell act as missing neighbors, and
        # boundary-boundary pairs are dropped (poisson_solve.hpp:896-965)
        types = self._cell_type_leaf
        active_pair = (
            (types[src] != self.SKIP_CELL)
            & (types[nbr] != self.SKIP_CELL)
            & ~(
                (types[src] == self.BOUNDARY_CELL)
                & (types[nbr] == self.BOUNDARY_CELL)
            )
        )

        half = 0.5 * grid.geometry.get_length(leaves.cells)   # (N, 3)
        # per-leaf center offsets toward face neighbors; missing neighbors
        # default to own size but give factor 0 (poisson_solve.hpp:716-724)
        pos_off = 2.0 * half.copy()
        neg_off = -2.0 * half.copy()
        has_pos = np.zeros((N, 3), dtype=bool)
        has_neg = np.zeros((N, 3), dtype=bool)
        for d in range(3):
            m = (direction == d + 1) & active_pair
            pos_off[src[m], d] = half[src[m], d] + half[nbr[m], d]
            has_pos[src[m], d] = True
            m = (direction == -(d + 1)) & active_pair
            neg_off[src[m], d] = -(half[src[m], d] + half[nbr[m], d])
            has_neg[src[m], d] = True

        total = pos_off - neg_off                        # (N, 3)
        f_pos = np.where(has_pos, 2.0 / (pos_off * total), 0.0)
        f_neg = np.where(has_neg, -2.0 / (neg_off * total), 0.0)
        scaling_leaf = -(f_pos.sum(-1) + f_neg.sum(-1))  # (N,)

        # per-entry multipliers at leaf level
        e_fwd = np.zeros(len(src))
        e_rev = np.zeros(len(src))
        for d in range(3):
            m = direction == d + 1
            e_fwd[m] = f_pos[src[m], d]
            e_rev[m] = f_neg[nbr[m], d]   # from n's view, c sits at -d
            m = direction == -(d + 1)
            e_fwd[m] = f_neg[src[m], d]
            e_rev[m] = f_pos[nbr[m], d]
        finer = nlen_i < slen_i           # neighbor finer than cell
        e_fwd = np.where(finer, e_fwd / 4.0, e_fwd)
        coarser = nlen_i > slen_i         # cell finer than neighbor
        e_rev = np.where(coarser, e_rev / 4.0, e_rev)
        nonface = (direction == 0) | ~active_pair
        e_fwd[nonface] = 0.0
        e_rev[nonface] = 0.0

        # scatter into [D, R, K] aligned with the epoch's gather tables
        ecol = np.arange(int(lists.start[-1]), dtype=np.int64) - np.repeat(
            lists.start[:-1], counts
        )
        owner = leaves.owner.astype(np.int64)
        mult_fwd = np.zeros((D, R, K))
        mult_rev = np.zeros((D, R, K))
        for d in range(D):
            sel = owner[src] == d
            rows = epoch.row_of[src[sel]]
            cols = ecol[sel]
            mult_fwd[d, rows, cols] = e_fwd[sel]
            mult_rev[d, rows, cols] = e_rev[sel]

        # diagonal + cell role for every row (ghosts included)
        scaling_rows = np.zeros((D, R))
        type_rows = np.full((D, R), self.SKIP_CELL, dtype=np.int8)
        for d in range(D):
            lp, gp = epoch.local_pos[d], epoch.ghost_pos[d]
            scaling_rows[d, : len(lp)] = scaling_leaf[lp]
            scaling_rows[d, len(lp) : len(lp) + len(gp)] = scaling_leaf[gp]
            type_rows[d, : len(lp)] = types[lp]
            type_rows[d, len(lp) : len(lp) + len(gp)] = types[gp]

        from ..parallel.mesh import put_table

        put = lambda a: put_table(a, self.grid.mesh, self.dtype)
        self._scaling = put(scaling_rows)
        # the [D, R, K] multiplier tables are only uploaded when the
        # gather path actually runs (solver fallback or residual()); when
        # the flat fast path engages they would otherwise pin
        # O(R*K) * 2 device memory as a diagnostics-only oracle
        self._mult_np = (mult_fwd, mult_rev)
        self._mult_dev = None
        self._scaling_np = scaling_rows
        self._volume = put(np.asarray(self.tables.length).prod(-1))
        solve_rows = np.asarray(self.tables.local_mask) & (
            type_rows == self.SOLVE_CELL
        )
        self._solve_mask = put_table(solve_rows, self.grid.mesh)
        # leaf-level factors kept for the flat dense fast path
        # (ops/flat_poisson.py): per-(leaf, axis) side factors + diagonal
        self._f_pos_leaf = f_pos
        self._f_neg_leaf = f_neg
        self._scaling_leaf = scaling_leaf

    # ----------------------------------------------------------- solver

    def _mult_table(self, i):
        """Device copy of the [D, R, K] multiplier table ``i`` (0 = fwd,
        1 = rev/transpose), uploaded on first gather-path use — per
        table, so residual() diagnostics on a flat-path solver only pin
        the forward one."""
        if self._mult_dev is None:
            self._mult_dev = [None, None]
        if self._mult_dev[i] is None:
            from ..parallel.mesh import put_table

            self._mult_dev[i] = put_table(
                self._mult_np[i], self.grid.mesh, self.dtype
            )
        return self._mult_dev[i]

    def _mult_tables(self):
        return self._mult_table(0), self._mult_table(1)

    def _apply(self, x, mult):
        """A·x (or Aᵀ·x with the transpose table): ghost-refresh then
        gather + ordered reduction."""
        x = self._exchange({"v": x})["v"]
        xn = gather_neighbors(x, self.tables.nbr_rows)
        return self._scaling * x + ordered_sum(mult * xn, axis=-1), x

    def _build_rolled(self):
        """(apply_fwd, apply_rev) on the rolled static-offset operator
        (ops/rolled_gather.py), or None when any device's offset
        histogram refuses the decomposition.  Each device's row block
        (local + ghost + scratch, ghosts refreshed by the halo exchange
        first — same contract as ``_apply``) is its own roll space;
        the union offset set keeps roll amounts trace-time constants.
        Semantically identical to ``_apply`` up to fp association
        (per-offset accumulation instead of the slot-ordered
        reduction)."""
        from ..ops.rolled_gather import (
            build_rolled_matvec_multi,
            make_rolled_apply_multi,
        )

        nbr = np.asarray(self.tables.nbr_rows)
        applies = []
        for mult in self._mult_np:
            t = build_rolled_matvec_multi(nbr, mult, self._scaling_np)
            if t is None:
                return None
            applies.append(make_rolled_apply_multi(
                t, jnp.dtype(self.dtype), mesh=self.grid.mesh))

        def wrap(ap):
            def run(x):
                x = self._exchange({"v": x})["v"]
                return ap(x)

            return run

        return wrap(applies[0]), wrap(applies[1])

    def _build_solver(self):
        """The BiCG loop, built over one of two operator spaces: the
        general gather tables ([1, R] rows) or the flat voxel grid when
        it qualifies — same algorithm, same stopping rules.  The plain
        gather-table form (no flat layout, no rolled decomposition — the
        AMR-churn shape) is pulled from the grid's executable cache with
        every table as a runtime argument, so rebuilds with the same
        shape signature never recompile the solve loop."""
        if self._flat is None and self._rolled is None:
            return self._build_gather_solver()
        local = self.tables.local_mask
        if self._flat is not None:
            apply_fwd, apply_rev, voxelize, writeback, masks = self._flat
            solve_mask = masks["solve"]
            dot_mask = masks["dot"]
            lift = voxelize
            project = writeback
        else:
            solve_mask = self._solve_mask
            dot_mask = solve_mask
            if self._rolled is not None:
                apply_fwd, apply_rev = self._rolled
            else:
                mult_fwd, mult_rev = self._mult_tables()
                apply_fwd = lambda v: self._apply(v, mult_fwd)[0]
                apply_rev = lambda v: self._apply(v, mult_rev)[0]
            # boundary cells keep their given solution values: they feed
            # the initial residual (Dirichlet lifting) but never change
            lift = lambda row_arr: jnp.where(local, row_arr, 0.0)
            project = lambda v: v

        def dot(a, b):
            w = jnp.where(dot_mask, a * b, 0.0)
            return jnp.sum(w, dtype=w.dtype)

        @jax.jit
        def solve(state, max_iterations, stop_residual, stop_after_increase):
            rhs = jnp.where(solve_mask, lift(state["rhs"]), 0.0)
            x = lift(state["solution"])

            Ax = apply_fwd(x)
            r0 = jnp.where(solve_mask, rhs - Ax, 0.0)
            r1 = r0
            p0, p1 = r0, r1
            dot_r = dot(r0, r1)
            res0 = jnp.sqrt(jnp.abs(dot(r0, r0)))

            # the reference keeps the minimum-residual solution and stops if
            # the residual grows a factor past it (AMR systems are
            # non-normal; BiCG semi-converges) — poisson_solve.hpp:246-250,
            # 655-683
            def cond(carry):
                i, x, r0, r1, p0, p1, dot_r, res, best_res, best_x = carry
                return (
                    (i < max_iterations)
                    & (res > stop_residual)
                    & (dot_r != 0)
                    & (res <= best_res * stop_after_increase)
                )

            def body(carry):
                i, x, r0, r1, p0, p1, dot_r, _, best_res, best_x = carry
                # restrict the operator to solve rows: boundary/skip rows
                # are local and never ghost-refreshed, so unmasked values
                # would leak into r and p (reference updates SOLVE cells
                # only, poisson_solve.hpp:405-520)
                Ap0 = jnp.where(solve_mask, apply_fwd(p0), 0.0)
                ATp1 = jnp.where(solve_mask, apply_rev(p1), 0.0)
                dot_p = dot(p1, Ap0)
                alpha = jnp.where(dot_p != 0, dot_r / dot_p, 0.0)
                x = x + alpha * p0
                r0 = r0 - alpha * Ap0
                r1 = r1 - alpha * ATp1
                new_dot_r = dot(r0, r1)
                beta = jnp.where(dot_r != 0, new_dot_r / dot_r, 0.0)
                p0 = r0 + beta * p0
                p1 = r1 + beta * p1
                res = jnp.sqrt(jnp.abs(dot(r0, r0)))
                better = res < best_res
                best_res = jnp.where(better, res, best_res)
                best_x = jnp.where(better, x, best_x)
                return (i + 1, x, r0, r1, p0, p1, new_dot_r, res, best_res, best_x)

            carry = (jnp.int32(0), x, r0, r1, p0, p1, dot_r, res0, res0, x)
            i, x, r0, r1, p0, p1, dot_r, res, best_res, best_x = jax.lax.while_loop(
                cond, body, carry
            )
            sol = jnp.where(local, project(best_x), 0.0)
            return {**state, "solution": sol}, best_res, i

        return solve

    def _build_gather_solver(self):
        """The cached-executable form of the gather-table BiCG solve:
        identical algorithm to :meth:`_build_solver`'s gather branch,
        with the halo schedule, gather table, masks and multiplier
        tables entering as jit arguments."""
        from ..parallel.exec_cache import traced_jit

        ex = self._exchange
        ex_body = ex.raw_body
        rings = tuple(ex.ring_send) + tuple(ex.ring_recv)

        def build():
            def solve(rings, nbr_rows, local, solve_mask, scaling,
                      mult_fwd, mult_rev, state, max_iterations,
                      stop_residual, stop_after_increase):
                def apply_mult(v, mult):
                    v = ex_body(*rings, {"v": v})["v"]
                    vn = gather_neighbors(v, nbr_rows)
                    return scaling * v + ordered_sum(mult * vn, axis=-1)

                def dot(a, b):
                    w = jnp.where(solve_mask, a * b, 0.0)
                    return jnp.sum(w, dtype=w.dtype)

                def lift(row_arr):
                    # boundary cells keep their given solution values:
                    # they feed the initial residual (Dirichlet lifting)
                    # but never change
                    return jnp.where(local, row_arr, 0.0)

                rhs = jnp.where(solve_mask, lift(state["rhs"]), 0.0)
                x = lift(state["solution"])

                Ax = apply_mult(x, mult_fwd)
                r0 = jnp.where(solve_mask, rhs - Ax, 0.0)
                r1 = r0
                p0, p1 = r0, r1
                dot_r = dot(r0, r1)
                res0 = jnp.sqrt(jnp.abs(dot(r0, r0)))

                def cond(carry):
                    (i, x, r0, r1, p0, p1, dot_r, res, best_res,
                     best_x) = carry
                    return (
                        (i < max_iterations)
                        & (res > stop_residual)
                        & (dot_r != 0)
                        & (res <= best_res * stop_after_increase)
                    )

                def body(carry):
                    i, x, r0, r1, p0, p1, dot_r, _, best_res, best_x = carry
                    Ap0 = jnp.where(
                        solve_mask, apply_mult(p0, mult_fwd), 0.0
                    )
                    ATp1 = jnp.where(
                        solve_mask, apply_mult(p1, mult_rev), 0.0
                    )
                    dot_p = dot(p1, Ap0)
                    alpha = jnp.where(dot_p != 0, dot_r / dot_p, 0.0)
                    x = x + alpha * p0
                    r0 = r0 - alpha * Ap0
                    r1 = r1 - alpha * ATp1
                    new_dot_r = dot(r0, r1)
                    beta = jnp.where(dot_r != 0, new_dot_r / dot_r, 0.0)
                    p0 = r0 + beta * p0
                    p1 = r1 + beta * p1
                    res = jnp.sqrt(jnp.abs(dot(r0, r0)))
                    better = res < best_res
                    best_res = jnp.where(better, res, best_res)
                    best_x = jnp.where(better, x, best_x)
                    return (i + 1, x, r0, r1, p0, p1, new_dot_r, res,
                            best_res, best_x)

                carry = (jnp.int32(0), x, r0, r1, p0, p1, dot_r, res0,
                         res0, x)
                (i, x, r0, r1, p0, p1, dot_r, res, best_res,
                 best_x) = jax.lax.while_loop(cond, body, carry)
                sol = jnp.where(local, best_x, 0.0)
                return {**state, "solution": sol}, best_res, i

            return traced_jit("poisson.solve", solve)

        fn = self.grid.exec_cache.get(
            ("poisson.solve", ex.structure_key, str(np.dtype(self.dtype))),
            build,
        )
        mult_fwd, mult_rev = self._mult_tables()
        args = (rings, self.tables.nbr_rows, self.tables.local_mask,
                self._solve_mask, self._scaling, mult_fwd, mult_rev)
        return lambda state, mi, sr, si: fn(*args, state, mi, sr, si)

    def _build_fast_solver(self):
        """Whole-solve fused BiCG kernel (ops/poisson_kernel.py): the
        entire masked iteration loop in one Pallas launch with every
        array VMEM-resident.  None when ineligible (no flat layout,
        multi-device, f64, too large, no Pallas); the XLA solver stays
        the fallback and the oracle (solutions agree to solver
        tolerance — the in-kernel dot association differs)."""
        from ..ops.dense_advection import have_pallas, pallas_available
        from ..ops.poisson_kernel import bicg_fits, make_bicg_solve

        t = self._flat_tables
        interpret = self.use_pallas == "interpret"
        if (
            not self.use_pallas
            or t is None
            or t["n_devices"] != 1
            # the whole-solve kernel's pool/broadcast is the 2-level
            # roll chain; 3+ level grids stay on the XLA flat matvec
            # (reshape-pyramid accumulation)
            or t.get("vl", 1) > 1
            or np.dtype(self.dtype) != np.float32
            or not bicg_fits(int(np.prod(t["shape"])))
            or not have_pallas()
            or not (interpret or pallas_available(np.float32))
        ):
            return None
        _fwd, _rev, voxelize, writeback, masks = self._flat
        local = self.tables.local_mask
        kern = make_bicg_solve(
            t["shape"], t["has_coarse"], interpret=interpret
        )
        f32 = lambda a: jnp.asarray(np.asarray(a), jnp.float32)
        statics = (
            [f32(w) for pair in t["weights"] for w in pair]
            + [f32(t["scaling"]), f32(t["fine"]), f32(~t["fine"]),
               f32(t["orig"]), f32(t["solve"]), f32(t["dot_mask"])]
        )
        solve_mask = masks["solve"]

        @jax.jit
        def solve_fast(state, max_iterations, stop_residual, stop_increase):
            rhs = jnp.where(solve_mask, voxelize(state["rhs"]), 0.0)
            x = voxelize(state["solution"])
            best_x, best_res, it = kern(
                rhs.astype(jnp.float32), x.astype(jnp.float32), *statics,
                max_iterations, stop_residual, stop_increase,
            )
            sol = jnp.where(local, writeback(best_x.astype(self.dtype)), 0.0)
            return {**state, "solution": sol}, best_res[0], it[0]

        return solve_fast

    def _disable_fast(self):
        self._solve_fast = None

    # ---------------------------------------------------------- user API

    def initialize_state(self, rhs_by_cell):
        grid = self.grid
        state = grid.new_state(self.spec)
        cells = grid.get_cells()
        rhs = np.asarray(rhs_by_cell, dtype=np.float64)
        # zero-mean the charge like the reference tests do for all-periodic
        # grids (volume-weighted so AMR stays consistent)
        vol = np.prod(grid.geometry.get_length(cells), axis=-1)
        if all(grid.topology.periodic) and self._full_solve:
            rhs = rhs - (rhs * vol).sum() / vol.sum()
        return grid.set_cell_data(state, "rhs", cells, rhs)

    def solve(
        self,
        state,
        max_iterations: int = 1000,
        stop_residual: float = 1e-12,
        stop_after_residual_increase: float = 10.0,
        restarts: int = 0,
    ):
        """Returns (state, best_residual, iterations).

        ``restarts``: BiCG on non-normal systems (AMR + mixed cell
        roles) can break down mid-Krylov-space and stop at the
        semi-convergence rule far from the target; re-invoking from the
        best solution rebuilds the space and recovers (the reference's
        drivers re-invoke solve for exactly this).  With ``restarts=N``
        the solve re-enters up to N more times until ``stop_residual``
        is met or an attempt makes no progress; iterations accumulate.
        Default 0 = the reference's single-trajectory behavior."""
        if restarts > 0:
            total_it = 0
            prev_res = float("inf")
            for _ in range(restarts + 1):
                state, res, it = self.solve(
                    state, max_iterations, stop_residual,
                    stop_after_residual_increase,
                )
                total_it += it
                if res <= stop_residual or not res < prev_res:
                    break  # converged, or the attempt made no progress
                prev_res = res
            return state, res, total_it
        # threshold dtype: f64 under x64, f32 otherwise — canonicalized
        # without the per-call truncation warning jnp.float64() emits
        import jax

        td = jax.dtypes.canonicalize_dtype(np.float64)
        if self._solve_fast is not None:
            from ..utils.fallback import fallback_call

            state, res, it = fallback_call(
                "fused Poisson BiCG kernel",
                lambda: self._solve_fast(
                    state, jnp.int32(max_iterations),
                    jnp.float32(stop_residual),
                    jnp.float32(stop_after_residual_increase),
                ),
                lambda: self._solve(
                    state, jnp.int32(max_iterations),
                    jnp.asarray(stop_residual, td),
                    jnp.asarray(stop_after_residual_increase, td),
                ),
                self._disable_fast,
            )
            return state, float(res), int(it)
        state, res, it = self._solve(
            state,
            jnp.int32(max_iterations),
            jnp.asarray(stop_residual, td),
            jnp.asarray(stop_after_residual_increase, td),
        )
        return state, float(res), int(it)

    def residual(self, state) -> float:
        Ax, _ = self._apply(state["solution"], self._mult_table(0))
        r = fetch(jnp.where(self._solve_mask, state["rhs"] - Ax, 0.0))
        return float(np.sqrt((r * r).sum()))
