from .advection import Advection
from .game_of_life import GameOfLife

__all__ = ["Advection", "GameOfLife"]
from .particles import Particles
from .poisson import Poisson
from .vlasov import Vlasov

__all__ += ["Particles", "Poisson", "Vlasov"]
