from .game_of_life import GameOfLife

__all__ = ["GameOfLife"]
