from .advection import Advection
from .game_of_life import GameOfLife

__all__ = ["Advection", "GameOfLife"]
