"""Stretched Cartesian geometry: arbitrary level-0 cell boundaries per
dimension, vectorized.

TPU-native re-design of the reference's
``dccrg_stretched_cartesian_geometry.hpp:45-828``: level-0 cell boundaries
are given as three monotone coordinate arrays; refined cells subdivide their
level-0 ancestor uniformly in index space, so all per-cell queries reduce to
index arithmetic plus a lookup into the boundary arrays.
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ..core.mapping import ERROR_CELL, ERROR_INDEX, Mapping
from ..core.topology import Topology

__all__ = ["StretchedCartesianGeometry"]


@dataclass(frozen=True)
class StretchedCartesianGeometry:
    mapping: Mapping
    topology: Topology = field(default_factory=Topology)
    #: three arrays of level-0 cell boundary coordinates, each of length
    #: mapping.length[d] + 1, strictly increasing
    coordinates: tuple = ()

    geometry_id = 2
    uniform_level0 = False  # per-dimension arbitrary cell boundaries

    def __post_init__(self):
        coords = tuple(np.asarray(c, dtype=np.float64) for c in self.coordinates)
        if len(coords) != 3:
            raise ValueError("coordinates must contain 3 arrays")
        for d, c in enumerate(coords):
            if len(c) != self.mapping.length[d] + 1:
                raise ValueError(
                    f"dimension {d}: need {self.mapping.length[d] + 1} boundary "
                    f"coordinates, got {len(c)}"
                )
            if not (np.diff(c) > 0).all():
                raise ValueError(f"dimension {d}: coordinates must be increasing")
        object.__setattr__(self, "coordinates", coords)

    # ------------------------------------------------------------- grid box

    def get_start(self) -> np.ndarray:
        return np.asarray([c[0] for c in self.coordinates])

    def get_end(self) -> np.ndarray:
        return np.asarray([c[-1] for c in self.coordinates])

    def get_level_0_cell_length(self) -> np.ndarray:
        """Not uniform here; returns the first level-0 cell's size (the
        reference has no such method for stretched grids — provided for
        duck-type compatibility in diagnostics only)."""
        return np.asarray([c[1] - c[0] for c in self.coordinates])

    # ------------------------------------------------------------ per cell

    def _minmax_1d(self, d: int, ind_d: np.ndarray, len_ind: np.ndarray):
        """Min and max coordinate along dimension d for cells starting at
        index ``ind_d`` with edge length ``len_ind`` index units."""
        upl = np.uint64(1) << np.uint64(self.mapping.max_refinement_level)
        c = self.coordinates[d]
        i0 = (ind_d // upl).astype(np.int64)  # level-0 cell index
        frac0 = (ind_d - i0.astype(np.uint64) * upl).astype(np.float64) / float(upl)
        frac1 = (ind_d + len_ind - i0.astype(np.uint64) * upl).astype(np.float64) / float(upl)
        width = c[i0 + 1] - c[i0]
        return c[i0] + frac0 * width, c[i0] + frac1 * width

    def get_min(self, cells) -> np.ndarray:
        ind = self.mapping.get_indices(cells)
        ln = self.mapping.get_cell_length_in_indices(cells)
        bad = ind[..., 0] == ERROR_INDEX
        ind = np.where(bad[..., None], 0, ind)
        ln = np.where(bad, 1, ln)
        out = np.stack(
            [self._minmax_1d(d, ind[..., d], ln)[0] for d in range(3)], axis=-1
        )
        out[bad] = np.nan
        return out

    def get_max(self, cells) -> np.ndarray:
        ind = self.mapping.get_indices(cells)
        ln = self.mapping.get_cell_length_in_indices(cells)
        bad = ind[..., 0] == ERROR_INDEX
        ind = np.where(bad[..., None], 0, ind)
        ln = np.where(bad, 1, ln)
        out = np.stack(
            [self._minmax_1d(d, ind[..., d], ln)[1] for d in range(3)], axis=-1
        )
        out[bad] = np.nan
        return out

    def get_length(self, cells) -> np.ndarray:
        return self.get_max(cells) - self.get_min(cells)

    def get_center(self, cells) -> np.ndarray:
        return 0.5 * (self.get_min(cells) + self.get_max(cells))

    # -------------------------------------------------------- coord queries

    def get_real_coordinate(self, coords) -> np.ndarray:
        coords = np.asarray(coords, dtype=np.float64)
        start, end = self.get_start(), self.get_end()
        span = end - start
        inside = (coords >= start) & (coords <= end)
        wrapped = start + np.mod(coords - start, span)
        periodic = np.asarray(self.topology.periodic, dtype=bool)
        return np.where(inside, coords, np.where(periodic, wrapped, np.nan))

    def get_indices(self, coords) -> np.ndarray:
        coords = self.get_real_coordinate(coords)
        upl = 1 << self.mapping.max_refinement_level
        out = np.empty(coords.shape, dtype=np.uint64)
        bad = np.isnan(coords)
        for d in range(3):
            c = self.coordinates[d]
            x = np.where(bad[..., d], c[0], coords[..., d])
            i0 = np.clip(np.searchsorted(c, x, side="right") - 1, 0, len(c) - 2)
            frac = (x - c[i0]) / (c[i0 + 1] - c[i0])
            sub = np.clip(np.floor(frac * upl), 0, upl - 1).astype(np.uint64)
            out[..., d] = np.uint64(i0) * np.uint64(upl) + sub
        out[bad] = ERROR_INDEX
        return out

    def get_cell(self, refinement_level: int, coords) -> np.ndarray:
        ind = self.get_indices(coords)
        bad = ind[..., 0] == ERROR_INDEX
        cell = self.mapping.get_cell_from_indices(
            np.where(bad[..., None], 0, ind), refinement_level
        )
        return np.where(bad, ERROR_CELL, cell)

    # ---------------------------------------------------------- file format

    def params_to_file_bytes(self) -> bytes:
        return b"".join(np.asarray(c, dtype="<f8").tobytes() for c in self.coordinates)

    @classmethod
    def params_from_file_bytes(cls, data: bytes, mapping: Mapping, topology: Topology):
        coords, off = [], 0
        for d in range(3):
            n = mapping.length[d] + 1
            coords.append(np.frombuffer(data[off : off + 8 * n], dtype="<f8"))
            off += 8 * n
        return cls(mapping=mapping, topology=topology, coordinates=tuple(coords)), off
