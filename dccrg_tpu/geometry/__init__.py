from .cartesian import CartesianGeometry, NoGeometry
from .stretched import StretchedCartesianGeometry

__all__ = ["CartesianGeometry", "NoGeometry", "StretchedCartesianGeometry"]


def geometry_from_id(geometry_id: int):
    """Map a serialized geometry_id back to its class (reference geometry_id
    constants: No=0, Cartesian=1, Stretched=2)."""
    return {
        NoGeometry.geometry_id: NoGeometry,
        CartesianGeometry.geometry_id: CartesianGeometry,
        StretchedCartesianGeometry.geometry_id: StretchedCartesianGeometry,
    }[geometry_id]
