"""Cartesian (uniform-cube) geometry, vectorized.

TPU-native re-design of the reference's ``dccrg_cartesian_geometry.hpp:49-768``
and ``dccrg_no_geometry.hpp:55-552``: the same duck-typed query surface
(start/end/length/center/min/max/coordinate->cell, periodic coordinate
wrapping) but every query takes *arrays* of cell ids or coordinates, so
geometry data (dx, centers) can be materialized as device arrays for kernels.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..core.mapping import ERROR_CELL, ERROR_INDEX, Mapping
from ..core.topology import Topology

__all__ = ["CartesianGeometry", "NoGeometry"]


@dataclass(frozen=True)
class CartesianGeometry:
    """Uniform cells: a start corner plus a level-0 cell size per dimension
    (reference ``Cartesian_Geometry_Parameters``,
    ``dccrg_cartesian_geometry.hpp:49-86``)."""

    mapping: Mapping
    topology: Topology = Topology()
    start: tuple[float, float, float] = (0.0, 0.0, 0.0)
    level_0_cell_length: tuple[float, float, float] = (1.0, 1.0, 1.0)

    geometry_id = 1
    #: every level-0 cell shares one physical size — the capability the
    #: dense/boxed/flat fast paths and the device particle re-bucket
    #: require before trusting get_level_0_cell_length as a global metric
    uniform_level0 = True

    def __post_init__(self):
        object.__setattr__(self, "start", tuple(float(v) for v in self.start))
        lengths = tuple(float(v) for v in self.level_0_cell_length)
        if any(v <= 0 for v in lengths):
            raise ValueError(f"level_0_cell_length must be positive: {lengths}")
        object.__setattr__(self, "level_0_cell_length", lengths)

    # ------------------------------------------------------------- grid box

    def get_start(self) -> np.ndarray:
        return np.asarray(self.start, dtype=np.float64)

    def get_end(self) -> np.ndarray:
        return self.get_start() + np.asarray(self.mapping.length, dtype=np.float64) * np.asarray(
            self.level_0_cell_length, dtype=np.float64
        )

    def get_level_0_cell_length(self) -> np.ndarray:
        return np.asarray(self.level_0_cell_length, dtype=np.float64)

    # ------------------------------------------------------------ per cell

    def _index_unit(self) -> np.ndarray:
        """Physical size of one index unit (max-refinement resolution)."""
        return self.get_level_0_cell_length() / float(1 << self.mapping.max_refinement_level)

    def get_length(self, cells) -> np.ndarray:
        """Cell edge lengths, shape ``cells.shape + (3,)``; NaN for invalid
        ids (reference ``dccrg_cartesian_geometry.hpp:282-309``)."""
        lvl = self.mapping.get_refinement_level(cells)
        valid = lvl >= 0
        scale = np.where(valid, 1.0 / (1 << np.where(valid, lvl, 0)), np.nan)
        return scale[..., None] * self.get_level_0_cell_length()

    def get_min(self, cells) -> np.ndarray:
        """Cell minimum corner coordinates."""
        ind = self.mapping.get_indices(cells)
        bad = ind[..., 0] == ERROR_INDEX
        out = self.get_start() + ind.astype(np.float64) * self._index_unit()
        out[bad] = np.nan
        return out

    def get_center(self, cells) -> np.ndarray:
        """Cell center coordinates; NaN for invalid ids
        (reference ``dccrg_cartesian_geometry.hpp:316-366``)."""
        return self.get_min(cells) + 0.5 * self.get_length(cells)

    def get_max(self, cells) -> np.ndarray:
        return self.get_min(cells) + self.get_length(cells)

    # -------------------------------------------------------- coord queries

    def get_real_coordinate(self, coords) -> np.ndarray:
        """Wrap coordinates into the grid box for periodic dimensions; NaN
        for outside coordinates in non-periodic dimensions
        (reference ``dccrg_cartesian_geometry.hpp:510-565``)."""
        coords = np.asarray(coords, dtype=np.float64)
        start, end = self.get_start(), self.get_end()
        span = end - start
        inside = (coords >= start) & (coords <= end)
        wrapped = start + np.mod(coords - start, span)
        periodic = np.asarray(self.topology.periodic, dtype=bool)
        return np.where(inside, coords, np.where(periodic, wrapped, np.nan))

    def get_indices(self, coords) -> np.ndarray:
        """Indices (max-ref resolution) containing given coordinates;
        ``ERROR_INDEX`` if outside (after periodic wrap)."""
        coords = self.get_real_coordinate(coords)
        unit = self._index_unit()
        rel = (coords - self.get_start()) / unit
        nmax = np.asarray(self.mapping.length_in_indices, dtype=np.float64)
        ok = ~np.isnan(rel)
        idx = np.clip(np.floor(np.where(ok, rel, 0)), 0, nmax - 1).astype(np.uint64)
        return np.where(ok, idx, ERROR_INDEX)

    def get_cell(self, refinement_level: int, coords) -> np.ndarray:
        """Cell of given refinement level at given coordinate(s);
        ``ERROR_CELL`` outside the grid
        (reference ``dccrg_cartesian_geometry.hpp:495-507``)."""
        ind = self.get_indices(coords)
        bad = ind[..., 0] == ERROR_INDEX
        out = self.mapping.get_cell_from_indices(
            np.where(bad[..., None], 0, ind), refinement_level
        )
        return np.where(bad, ERROR_CELL, out)

    # ---------------------------------------------------------- file format

    def params_to_file_bytes(self) -> bytes:
        return (
            np.asarray(self.start, dtype="<f8").tobytes()
            + np.asarray(self.level_0_cell_length, dtype="<f8").tobytes()
        )

    @classmethod
    def params_from_file_bytes(cls, data: bytes, mapping: Mapping, topology: Topology):
        vals = np.frombuffer(data[:48], dtype="<f8")
        return (
            cls(
                mapping=mapping,
                topology=topology,
                start=tuple(vals[:3]),
                level_0_cell_length=tuple(vals[3:6]),
            ),
            48,
        )


class NoGeometry(CartesianGeometry):
    """Trivial geometry: every level-0 cell is a unit cube starting at the
    origin (reference ``dccrg_no_geometry.hpp:55-552``)."""

    geometry_id = 0

    def __init__(self, mapping: Mapping, topology: Topology = Topology(), **_ignored):
        super().__init__(mapping=mapping, topology=topology)

    def params_to_file_bytes(self) -> bytes:
        return b""

    @classmethod
    def params_from_file_bytes(cls, data: bytes, mapping: Mapping, topology: Topology):
        return cls(mapping=mapping, topology=topology), 0
