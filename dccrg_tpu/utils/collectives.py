"""Host-metadata collectives — the role of the reference's MPI support
layer (``dccrg_mpi_support.hpp``: ``All_Gather`` ``:98-231``,
``All_Reduce`` ``:237-266``, ``Some_Reduce`` ``:282-377``).

Two regimes:

* **Device-wide reductions** belong in jitted code (``jnp.sum``/``jnp.min``
  over sharded arrays lower to XLA collectives over ICI) — nothing here.
* **Host-side metadata** (refine-request sets, directory updates, cell
  weights) must agree across *controllers*.  Under JAX's single-controller
  model one Python process drives every device, so agreement is free and
  the helpers degenerate to identities.  Under multi-controller SPMD
  (``jax.distributed.initialize``, one process per host, the deployment
  the reference reaches with one MPI rank per node) each process holds its
  own copies, and the helpers below really move data: variable-length
  uint64 sets travel as (length allgather, padded payload allgather) via
  ``jax.experimental.multihost_utils.process_allgather``, which lowers to
  an XLA all_gather across processes over ICI/DCN.

The multi-controller path is exercised degenerately by the 1-process case
and, in tests, by substituting the transport (see
``tests/test_collectives.py``); ARCHITECTURE.md §multi-host records what a
full multi-host deployment additionally requires.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "process_count",
    "fetch",
    "allgather_u64",
    "allgather_u64_multi",
    "union_u64",
    "sync_adaptation",
    "sync_partition_inputs",
    "barrier",
    "all_gather",
    "all_reduce",
    "some_reduce",
    "halo_peers",
]


def process_count() -> int:
    """Number of controller processes (1 unless jax.distributed is up)."""
    import jax

    return jax.process_count()


def fetch(x, dtype=None) -> np.ndarray:
    """Device→host readback valid under any controller layout.

    Single-controller arrays (and replicated jit outputs) are fully
    addressable and convert directly; an array sharded across *other
    processes'* devices is first all-gathered to every host
    (``process_allgather(tiled=True)`` lowers to one XLA all_gather),
    matching the reference's rule that host-side consumers only ever see
    replicated data (``dccrg.hpp:7196``'s directory invariant).
    """
    if getattr(x, "is_fully_addressable", True):
        out = np.asarray(x)
    else:
        from jax.experimental import multihost_utils

        out = np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return out if dtype is None else out.astype(dtype, copy=False)


def _process_allgather(x: np.ndarray) -> np.ndarray:
    """Transport seam: gather one fixed-shape array from every process;
    returns ``[P, *x.shape]``.  Split out so tests can substitute a fake
    multi-process transport."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x))


def allgather_u64_multi(arrays: list) -> list[list]:
    """Gather several variable-length uint64 arrays from every process in
    ONE (lengths, payload) collective pair — the wire format for all
    id-set agreement (the reference's ``All_Gather`` of cell-id lists,
    ``dccrg_mpi_support.hpp:98-231``).  Returns ``out[p][i]`` = process
    p's i-th array.  Single-controller: ``[arrays]``.

    Wire format: one ``[k]`` length vector gather, then the concatenated
    payloads padded to the max total — two fixed-shape collectives, which
    is all ``process_allgather`` speaks, independent of how many sets
    travel together.
    """
    arrays = [np.ascontiguousarray(a, dtype=np.uint64) for a in arrays]
    if process_count() == 1:
        return [arrays]
    k = len(arrays)
    lens = np.asarray([len(a) for a in arrays], dtype=np.int64)
    all_lens = _process_allgather(lens)               # [P, k]
    cap = max(int(all_lens.sum(axis=1).max()), 1)
    buf = np.zeros(cap, dtype=np.uint64)
    cat = np.concatenate(arrays) if k else buf[:0]
    buf[: len(cat)] = cat
    bufs = _process_allgather(buf)                    # [P, cap]
    out = []
    for p in range(len(bufs)):
        bounds = np.concatenate(([0], np.cumsum(all_lens[p])))
        out.append([bufs[p, bounds[i] : bounds[i + 1]] for i in range(k)])
    return out


def allgather_u64(values: np.ndarray) -> list[np.ndarray]:
    """Every process's (variable-length) uint64 array, visible
    everywhere.  Single-controller: ``[values]``."""
    return [row[0] for row in allgather_u64_multi([values])]


def union_u64(values) -> np.ndarray:
    """Sorted union of every process's uint64 set — how structural
    mutation requests (refine/unrefine/veto sets) reach agreement before a
    commit: each controller queues requests for cells it knows about, the
    union is what the deterministic commit pipeline runs on everywhere
    (reference: per-rank request lists merged in ``dccrg.hpp:3461-3485``'s
    all-to-all of induced refines)."""
    arr = (
        values
        if isinstance(values, np.ndarray)
        else np.fromiter(values, dtype=np.uint64)
    )
    parts = allgather_u64(arr)
    return np.unique(np.concatenate(parts))


def sync_adaptation(queues) -> None:
    """Merge every controller's AMR request queues in place — the
    agreement step before ``commit_adaptation`` runs the deterministic
    veto→induce→override→execute pipeline on identical inputs everywhere.
    Unions are correct for requests (any controller's request stands) and
    for vetoes (any controller's veto stands), matching the reference's
    cross-rank request exchange (``dccrg.hpp:3461-3485``).  Identity with
    one controller."""
    if process_count() == 1:
        return
    names = ("to_refine", "to_unrefine", "not_to_refine", "not_to_unrefine")
    rows = allgather_u64_multi(
        [np.fromiter(getattr(queues, name), dtype=np.uint64) for name in names]
    )
    for i, name in enumerate(names):
        merged = np.unique(np.concatenate([row[i] for row in rows]))
        setattr(queues, name, {int(c) for c in merged})


def sync_partition_inputs(pin_requests: dict, cell_weights: dict) -> tuple:
    """The merged (pins, weights) view across every controller — the
    agreement step before ``balance_load`` partitions, mirroring the
    reference's ``update_pin_requests`` All_Gather of per-rank pins
    (``dccrg.hpp:8297-8340``) and its replicated cell-weight map.

    Returns a TRANSIENT merged pair; the caller's own dicts stay local
    (the reference likewise gathers into ``all_pin_requests`` while each
    rank's ``pin_requests`` remains its own), so a later local unpin or
    re-pin is not resurrected by stale copies inherited from peers.

    Both dicts travel as (cell-id array, value array) pairs in the one
    lengths+padded-payload wire format (weights bitcast to uint64).
    Merge order is process rank: when two controllers disagree about the
    same cell, the highest rank's entry wins — deterministic, and every
    process applies the identical rule.  Identity with one controller."""
    if process_count() == 1:
        return pin_requests, cell_weights
    pin_cells = np.fromiter(pin_requests.keys(), dtype=np.uint64,
                            count=len(pin_requests))
    pin_devs = np.fromiter(pin_requests.values(), dtype=np.uint64,
                           count=len(pin_requests))
    w_cells = np.fromiter(cell_weights.keys(), dtype=np.uint64,
                          count=len(cell_weights))
    w_vals = np.fromiter(cell_weights.values(), dtype=np.float64,
                         count=len(cell_weights)).view(np.uint64)
    rows = allgather_u64_multi([pin_cells, pin_devs, w_cells, w_vals])
    merged_pins, merged_weights = {}, {}
    for row in rows:                       # ascending process rank
        for c, d in zip(row[0], row[1]):
            merged_pins[int(c)] = int(d)
        for c, w in zip(row[2], row[3].view(np.float64)):
            merged_weights[int(c)] = float(w)
    return merged_pins, merged_weights


def barrier(name: str = "dccrg") -> None:
    """Cross-controller synchronization point (the role of
    ``MPI_Barrier`` around the reference's collective file IO,
    ``dccrg.hpp:1128``).  Identity with one controller."""
    if process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def all_gather(per_device_values) -> list:
    """Every device's value, visible everywhere (reference All_Gather).
    Per-device metadata lives replicated on the controller, so this is the
    list itself; cross-process gathering is ``allgather_u64``."""
    return list(per_device_values)


def all_reduce(per_device_values, op=np.add):
    """Reduce all devices' values to one result (reference All_Reduce).
    Under multiple controllers each process reduces its devices' values
    locally, the partials are gathered, and ``op`` reduces them again —
    valid for any associative ufunc (add, minimum, maximum, ...)."""
    local = op.reduce(np.asarray(per_device_values), axis=0)
    if process_count() == 1:
        return local
    parts = _process_allgather(np.asarray(local))
    return op.reduce(parts, axis=0)


def halo_peers(grid, device: int, hood_id=None) -> np.ndarray:
    """Devices that exchange halo cells with the given one."""
    pc = grid.epoch.hoods[hood_id].pair_counts
    return np.flatnonzero((pc[device] > 0) | (pc[:, device] > 0))


def some_reduce(grid, per_device_values, device: int, op=np.add, hood_id=None):
    """Reduce only among a device and its halo peers — the reference's
    neighbor-only point-to-point reduce (``Some_Reduce``), whose peer set
    here comes from the halo schedule instead of explicit rank lists."""
    peers = halo_peers(grid, device, hood_id)
    vals = np.asarray(per_device_values)
    members = np.unique(np.concatenate([[device], peers]))
    return op.reduce(vals[members], axis=0)
