"""Reduction utilities — the role of the reference's MPI support layer
(``dccrg_mpi_support.hpp``: ``All_Gather`` ``:98-231``, ``All_Reduce``
``:237-266``, ``Some_Reduce`` ``:282-377``).

Device-wide reductions belong in jitted code (``jnp.sum``/``jnp.min`` over
sharded arrays lower to XLA collectives over ICI); these helpers cover the
host-side metadata reductions the reference does between ranks.  Under a
single controller an "All_Gather" is trivially the array itself — kept as a
named function so call sites document intent and a future multi-controller
backend (jax.distributed) has one seam to fill.
"""
from __future__ import annotations

import numpy as np

__all__ = ["all_gather", "all_reduce", "some_reduce", "halo_peers"]


def all_gather(per_device_values) -> list:
    """Every device's value, visible everywhere (reference All_Gather)."""
    return list(per_device_values)


def all_reduce(per_device_values, op=np.add):
    """Reduce all devices' values to one result (reference All_Reduce)."""
    return op.reduce(np.asarray(per_device_values), axis=0)


def halo_peers(grid, device: int, hood_id=None) -> np.ndarray:
    """Devices that exchange halo cells with the given one."""
    pc = grid.epoch.hoods[hood_id].pair_counts
    return np.flatnonzero((pc[device] > 0) | (pc[:, device] > 0))


def some_reduce(grid, per_device_values, device: int, op=np.add, hood_id=None):
    """Reduce only among a device and its halo peers — the reference's
    neighbor-only point-to-point reduce (``Some_Reduce``), whose peer set
    here comes from the halo schedule instead of explicit rank lists."""
    peers = halo_peers(grid, device, hood_id)
    vals = np.asarray(per_device_values)
    members = np.unique(np.concatenate([[device], peers]))
    return op.reduce(vals[members], axis=0)
