"""Host-metadata collectives — the role of the reference's MPI support
layer (``dccrg_mpi_support.hpp``: ``All_Gather`` ``:98-231``,
``All_Reduce`` ``:237-266``, ``Some_Reduce`` ``:282-377``).

Two regimes:

* **Device-wide reductions** belong in jitted code (``jnp.sum``/``jnp.min``
  over sharded arrays lower to XLA collectives over ICI) — nothing here.
* **Host-side metadata** (refine-request sets, directory updates, cell
  weights) must agree across *controllers*.  Under JAX's single-controller
  model one Python process drives every device, so agreement is free and
  the helpers degenerate to identities.  Under multi-controller SPMD
  (``jax.distributed.initialize``, one process per host, the deployment
  the reference reaches with one MPI rank per node) each process holds its
  own copies, and the helpers below really move data: variable-length
  uint64 sets travel as (length allgather, padded payload allgather) via
  ``jax.experimental.multihost_utils.process_allgather``, which lowers to
  an XLA all_gather across processes over ICI/DCN.

The multi-controller path is exercised degenerately by the 1-process case
and, in tests, by substituting the transport (see
``tests/test_collectives.py``); ARCHITECTURE.md §multi-host records what a
full multi-host deployment additionally requires.
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "process_count",
    "retrying",
    "fetch",
    "allgather_u64",
    "allgather_u64_multi",
    "union_u64",
    "sync_adaptation",
    "sync_partition_inputs",
    "assert_agreement",
    "barrier",
    "all_gather",
    "all_reduce",
    "some_reduce",
    "some_reduce_p2p",
    "halo_peers",
]


def process_count() -> int:
    """Number of controller processes (1 unless jax.distributed is up)."""
    import jax

    return jax.process_count()


# --------------------------------------------------------------- retry plane

def _retry_budget() -> int:
    import os

    return int(os.environ.get("DCCRG_P2P_RETRIES", "4"))


def _retry_base() -> float:
    import os

    return float(os.environ.get("DCCRG_P2P_RETRY_BASE", "0.05"))


def retrying(fn, what: str, peer=None, budget: int | None = None,
             base: float | None = None, cap: float = 2.0):
    """Run ``fn()`` with bounded exponential backoff + jitter on
    transient ``OSError``s — the retry discipline for the controller p2p
    transport's connect/accept/recv operations (ISSUE 4d).

    Timeouts are NOT retried (each socket op already carries the long
    ``DCCRG_P2P_TIMEOUT`` budget; retrying one would multiply it), and
    neither is anything that is not an ``OSError``.  Each retry is
    counted as ``p2p.retries{peer=...}``; once the budget
    (``DCCRG_P2P_RETRIES``, default 4) is spent, a diagnostic
    ``RuntimeError`` names the operation, peer, budget, and last error
    — a clean abort instead of a hung or half-done exchange.
    """
    import random
    import socket
    import time

    from ..obs import metrics

    budget = _retry_budget() if budget is None else int(budget)
    base = _retry_base() if base is None else float(base)
    attempt = 0
    while True:
        try:
            return fn()
        except OSError as e:
            if isinstance(e, (socket.timeout, TimeoutError)):
                raise
            attempt += 1
            if attempt > budget:
                raise RuntimeError(
                    f"p2p {what}"
                    + (f" (peer {peer})" if peer is not None else "")
                    + f": retry budget of {budget} exhausted "
                    f"(last error: {e!r}); raise DCCRG_P2P_RETRIES if the "
                    "network is transiently flaky, or investigate the peer"
                ) from e
            metrics.inc("p2p.retries",
                        peer="?" if peer is None else str(peer))
            # full jitter on an exponential envelope (AWS-style): the
            # sleep is uniform in (0, base * 2^(attempt-1)], capped
            time.sleep(random.uniform(0.0, min(cap, base * 2 ** (attempt - 1))))


def fetch(x, dtype=None) -> np.ndarray:
    """Device→host readback valid under any controller layout.

    Single-controller arrays (and replicated jit outputs) are fully
    addressable and convert directly; an array sharded across *other
    processes'* devices is first all-gathered to every host
    (``process_allgather(tiled=True)`` lowers to one XLA all_gather),
    matching the reference's rule that host-side consumers only ever see
    replicated data (``dccrg.hpp:7196``'s directory invariant).
    """
    if getattr(x, "is_fully_addressable", True):
        out = np.asarray(x)
    else:
        from jax.experimental import multihost_utils

        out = np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return out if dtype is None else out.astype(dtype, copy=False)


def _process_allgather(x: np.ndarray) -> np.ndarray:
    """Transport seam: gather one fixed-shape array from every process;
    returns ``[P, *x.shape]``.  Split out so tests can substitute a fake
    multi-process transport."""
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x))


def allgather_u64_multi(arrays: list) -> list[list]:
    """Gather several variable-length uint64 arrays from every process in
    ONE (lengths, payload) collective pair — the wire format for all
    id-set agreement (the reference's ``All_Gather`` of cell-id lists,
    ``dccrg_mpi_support.hpp:98-231``).  Returns ``out[p][i]`` = process
    p's i-th array.  Single-controller: ``[arrays]``.

    Wire format: one ``[k]`` length vector gather, then the concatenated
    payloads padded to the max total — two fixed-shape collectives, which
    is all ``process_allgather`` speaks, independent of how many sets
    travel together.
    """
    arrays = [np.ascontiguousarray(a, dtype=np.uint64) for a in arrays]
    if process_count() == 1:
        return [arrays]
    k = len(arrays)
    lens = np.asarray([len(a) for a in arrays], dtype=np.int64)
    all_lens = _process_allgather(lens)               # [P, k]
    cap = max(int(all_lens.sum(axis=1).max()), 1)
    buf = np.zeros(cap, dtype=np.uint64)
    cat = np.concatenate(arrays) if k else buf[:0]
    buf[: len(cat)] = cat
    bufs = _process_allgather(buf)                    # [P, cap]
    out = []
    for p in range(len(bufs)):
        bounds = np.concatenate(([0], np.cumsum(all_lens[p])))
        out.append([bufs[p, bounds[i] : bounds[i + 1]] for i in range(k)])
    return out


def allgather_u64(values: np.ndarray) -> list[np.ndarray]:
    """Every process's (variable-length) uint64 array, visible
    everywhere.  Single-controller: ``[values]``."""
    return [row[0] for row in allgather_u64_multi([values])]


def union_u64(values) -> np.ndarray:
    """Sorted union of every process's uint64 set — how structural
    mutation requests (refine/unrefine/veto sets) reach agreement before a
    commit: each controller queues requests for cells it knows about, the
    union is what the deterministic commit pipeline runs on everywhere
    (reference: per-rank request lists merged in ``dccrg.hpp:3461-3485``'s
    all-to-all of induced refines)."""
    arr = (
        values
        if isinstance(values, np.ndarray)
        else np.fromiter(values, dtype=np.uint64)
    )
    parts = allgather_u64(arr)
    return np.unique(np.concatenate(parts))


def sync_adaptation(queues) -> None:
    """Merge every controller's AMR request queues in place — the
    agreement step before ``commit_adaptation`` runs the deterministic
    veto→induce→override→execute pipeline on identical inputs everywhere.
    Unions are correct for requests (any controller's request stands) and
    for vetoes (any controller's veto stands), matching the reference's
    cross-rank request exchange (``dccrg.hpp:3461-3485``).  Identity with
    one controller."""
    if process_count() == 1:
        return
    names = ("to_refine", "to_unrefine", "not_to_refine", "not_to_unrefine")
    rows = allgather_u64_multi(
        [np.fromiter(getattr(queues, name), dtype=np.uint64) for name in names]
    )
    for i, name in enumerate(names):
        merged = np.unique(np.concatenate([row[i] for row in rows]))
        setattr(queues, name, {int(c) for c in merged})


def sync_partition_inputs(pin_requests: dict, cell_weights: dict) -> tuple:
    """The merged (pins, weights) view across every controller — the
    agreement step before ``balance_load`` partitions, mirroring the
    reference's ``update_pin_requests`` All_Gather of per-rank pins
    (``dccrg.hpp:8297-8340``) and its replicated cell-weight map.

    Returns a TRANSIENT merged pair; the caller's own dicts stay local
    (the reference likewise gathers into ``all_pin_requests`` while each
    rank's ``pin_requests`` remains its own), so a later local unpin or
    re-pin is not resurrected by stale copies inherited from peers.

    Both dicts travel as (cell-id array, value array) pairs in the one
    lengths+padded-payload wire format (weights bitcast to uint64).
    Merge order is process rank: when two controllers disagree about the
    same cell, the highest rank's entry wins — deterministic, and every
    process applies the identical rule.  Identity with one controller."""
    if process_count() == 1:
        return pin_requests, cell_weights
    pin_cells = np.fromiter(pin_requests.keys(), dtype=np.uint64,
                            count=len(pin_requests))
    pin_devs = np.fromiter(pin_requests.values(), dtype=np.uint64,
                           count=len(pin_requests))
    w_cells = np.fromiter(cell_weights.keys(), dtype=np.uint64,
                          count=len(cell_weights))
    w_vals = np.fromiter(cell_weights.values(), dtype=np.float64,
                         count=len(cell_weights)).view(np.uint64)
    rows = allgather_u64_multi([pin_cells, pin_devs, w_cells, w_vals])
    merged_pins, merged_weights = {}, {}
    for row in rows:                       # ascending process rank
        for c, d in zip(row[0], row[1]):
            merged_pins[int(c)] = int(d)
        for c, w in zip(row[2], row[3].view(np.float64)):
            merged_weights[int(c)] = float(w)
    return merged_pins, merged_weights


def assert_agreement(tag: str, payload: bytes) -> None:
    """ENFORCED multi-controller agreement for host-side mutator inputs
    (VERDICT-r4 missing 4): hash the local inputs and compare across
    every controller over the collectives seam; any mismatch raises on
    ALL controllers (each sees the differing digest) instead of letting
    the grids silently diverge.  The reference gets this structurally
    from SPMD collectives (``dccrg.hpp:6383-6603``); here the helpers
    the mutators run on are host-local, so agreement must be checked.
    Identity with one controller."""
    if process_count() == 1:
        return
    import hashlib

    # the tag is part of the digest: two DIFFERENT mutators with
    # coincidentally equal payload bytes must not falsely agree
    digest = np.frombuffer(
        hashlib.sha256(tag.encode() + b"\0" + payload).digest()[:8],
        dtype=np.uint64,
    ).copy()
    rows = allgather_u64(digest)
    mine = int(digest[0])
    bad = [p for p, r in enumerate(rows) if int(r[0]) != mine]
    if bad:
        raise RuntimeError(
            f"controllers disagree on {tag}: this process's inputs "
            f"differ from process(es) {bad} — {tag} must be called with "
            "identical arguments on every controller"
        )


def barrier(name: str = "dccrg") -> None:
    """Cross-controller synchronization point (the role of
    ``MPI_Barrier`` around the reference's collective file IO,
    ``dccrg.hpp:1128``).  Identity with one controller."""
    if process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def all_gather(per_device_values) -> list:
    """Every device's value, visible everywhere (reference All_Gather).
    Per-device metadata lives replicated on the controller, so this is the
    list itself; cross-process gathering is ``allgather_u64``."""
    return list(per_device_values)


def all_reduce(per_device_values, op=np.add):
    """Reduce all devices' values to one result (reference All_Reduce).
    Under multiple controllers each process reduces its devices' values
    locally, the partials are gathered, and ``op`` reduces them again —
    valid for any associative ufunc (add, minimum, maximum, ...)."""
    local = op.reduce(np.asarray(per_device_values), axis=0)
    if process_count() == 1:
        return local
    parts = _process_allgather(np.asarray(local))
    return op.reduce(parts, axis=0)


def halo_peers(grid, device: int, hood_id=None) -> np.ndarray:
    """Devices that exchange halo cells with the given one."""
    pc = grid.epoch.hoods[hood_id].pair_counts
    return np.flatnonzero((pc[device] > 0) | (pc[:, device] > 0))


class _P2PTransport:
    """Point-to-point controller transport — the role of the reference's
    ``MPI_Isend``/``MPI_Irecv`` pairs in ``Some_Reduce``
    (``dccrg_mpi_support.hpp:282-377``): per exchange, a message travels
    to and from EACH neighbor process individually; no process outside
    the neighbor set takes part and no collective runs.

    Bootstrap is one global collective (the address book gathers every
    process's (ip, port) via the allgather seam — the ``MPI_Init`` of
    this layer); after that, exchanges open fresh TCP connections only
    between the participating pairs.  Deadlock-free by orientation: the
    lower rank of each pair connects, the higher rank accepts, and an
    initiator reads its response before its call returns, which
    serializes each pair's exchanges (the per-pair sequence number in
    the header asserts it).  Byte counts per peer are recorded in
    ``sent_to``/``received_from`` so tests can check the transport
    really is neighbor-only."""

    _instance = None

    @classmethod
    def get(cls) -> "_P2PTransport":
        """The per-process singleton.  FIRST call is a global collective
        (every process must reach it) — ``some_reduce`` guarantees this
        because every controller calls it; direct ``some_reduce_p2p``
        users must uphold it on first use."""
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def __init__(self):
        import secrets
        import socket
        import struct

        import jax

        self.rank = jax.process_index()
        self.sent_to: dict[int, int] = {}
        self.received_from: dict[int, int] = {}
        self._pair_seq: dict[int, int] = {}
        #: connections accepted from peers that are ahead of us (already
        #: in a later exchange whose peer set includes us while ours for
        #: the current exchange does not) — consumed when we get there
        self._pending: dict[int, tuple[int, bytes, object]] = {}
        # bind to the advertised interface, not 0.0.0.0: the port should
        # only be reachable the way peers will actually dial it
        ip = self._advertised_ip()
        self._listener = socket.socket()
        try:
            self._listener.bind((ip, 0))
        except OSError:
            # the advertised address may not be a local bindable address
            # in NAT'd topologies (DCCRG_P2P_HOST names the public side)
            self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(128)
        port = self._listener.getsockname()[1]
        ip_u32 = struct.unpack("!I", socket.inet_aton(ip))[0]
        # per-job shared token: every process contributes random bits and
        # the XOR travels only over the jax-distributed allgather, so any
        # party outside the job cannot know it; message headers carrying a
        # different token are rejected instead of consumed
        token_part = secrets.randbits(64)
        book = _process_allgather(
            np.asarray([ip_u32, port, token_part], dtype=np.uint64)
        )
        book = np.atleast_2d(book)
        self.token = int(np.bitwise_xor.reduce(book[:, 2].astype(np.uint64)))
        self.addrs = [
            (socket.inet_ntoa(struct.pack("!I", int(row[0]))), int(row[1]))
            for row in book
        ]

    @staticmethod
    def _advertised_ip() -> str:
        """The address peers should dial: the interface that routes to
        the jax coordinator (a UDP connect learns the outbound interface
        without sending a packet) — gethostbyname commonly resolves to
        127.0.0.1, which other HOSTS cannot dial.  ``DCCRG_P2P_HOST``
        overrides for unusual network topologies."""
        import os
        import socket

        override = os.environ.get("DCCRG_P2P_HOST")
        if override:
            return socket.gethostbyname(override)
        try:
            from jax._src.distributed import global_state

            coord = global_state.coordinator_address
            host, port = coord.rsplit(":", 1)
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                s.connect((host, int(port)))
                return s.getsockname()[0]
        except Exception:  # noqa: BLE001 - fall back to name resolution
            pass
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"

    #: sender rank, per-pair sequence, shared job token, payload bytes
    _HEADER = "!IIQI"

    @staticmethod
    def _timeout() -> float:
        """Per-socket-operation timeout (seconds).  Large payloads on a
        congested link or a peer stuck in a long XLA compile may
        legitimately need more than the default; ``DCCRG_P2P_TIMEOUT``
        raises it without code changes."""
        import os

        return float(os.environ.get("DCCRG_P2P_TIMEOUT", "120"))

    @staticmethod
    def _recvn(sock, n: int, peer=None) -> bytes:
        """Receive exactly ``n`` bytes.  Each ``recv`` runs under the
        retry plane (transient ``OSError``s back off and retry within
        the ``DCCRG_P2P_RETRIES`` budget; the ``p2p.recv`` injection
        site fires before the real call, so armed faults exercise the
        retry path without touching the kernel).  A retried ``recv``
        re-requests only the still-missing bytes — nothing was consumed
        when the previous attempt raised."""
        from ..resilience import inject

        chunks = []
        while n:
            def attempt(want=n):
                inject.maybe_raise("p2p.recv")
                return sock.recv(want)

            b = retrying(attempt, "recv", peer=peer)
            if not b:
                raise ConnectionError("p2p peer closed mid-message")
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def exchange(self, payload: bytes, peers) -> dict[int, bytes]:
        """Symmetric send+receive of ``payload`` with every process in
        ``peers`` (collective among exactly those processes + self).
        Returns {peer: its payload}.

        Every send runs in its own thread (the reference's ``MPI_Isend``
        posture): the main thread only reads, so no send-blocking cycle
        can form in a fully-connected clique regardless of payload size
        vs kernel socket buffers.  A connection arriving from a peer
        that is already in a LATER exchange (one whose peer set includes
        us while our current one does not include it) is stashed and
        consumed when we reach that exchange; the peer simply blocks in
        its read until then, which is ordinary collective alignment."""
        import socket
        import struct
        import threading
        import warnings

        timeout = self._timeout()
        peers = sorted({int(p) for p in peers} - {self.rank})
        out: dict[int, bytes] = {}
        conns = []
        senders = []
        errors = []

        def send_all(sock, data):
            try:
                sock.sendall(data)
            except OSError as e:  # surfaced after the joins below
                errors.append(e)

        def spawn_send(sock, data):
            t = threading.Thread(target=send_all, args=(sock, data),
                                 daemon=True)
            t.start()
            senders.append(t)

        hdr_n = struct.calcsize(self._HEADER)
        # initiate toward higher ranks (lower rank of each pair connects)
        from ..resilience import inject

        for p in (q for q in peers if q > self.rank):
            seq = self._pair_seq[p] = self._pair_seq.get(p, 0) + 1
            try:
                def connect(peer=p):
                    inject.maybe_raise("p2p.connect",
                                       exc=ConnectionRefusedError)
                    return socket.create_connection(
                        self.addrs[peer], timeout=timeout
                    )

                s = retrying(connect, "connect", peer=p)
            except (socket.timeout, TimeoutError) as e:
                raise TimeoutError(
                    f"p2p connect to process {p} (pair seq {seq}) timed "
                    f"out after {timeout}s; raise DCCRG_P2P_TIMEOUT if "
                    "the peer is legitimately slow"
                ) from e
            s.settimeout(timeout)
            spawn_send(s, struct.pack(self._HEADER, self.rank, seq,
                                      self.token, len(payload)) + payload)
            conns.append((p, seq, s))
            self.sent_to[p] = self.sent_to.get(p, 0) + len(payload)

        def serve_lower(rk, seq, body, conn):
            my_seq = self._pair_seq[rk] = self._pair_seq.get(rk, 0) + 1
            if seq != my_seq:
                raise RuntimeError(
                    f"p2p exchange out of step with process {rk} "
                    f"(seq {seq} != {my_seq})"
                )
            out[rk] = body
            spawn_send(conn, struct.pack(self._HEADER, self.rank, my_seq,
                                         self.token, len(payload)) + payload)
            self.received_from[rk] = self.received_from.get(rk, 0) + len(body)
            self.sent_to[rk] = self.sent_to.get(rk, 0) + len(payload)

        # accept from lower ranks (stashed connections first)
        expect = {q for q in peers if q < self.rank}
        served = []
        for rk in sorted(expect & set(self._pending)):
            seq, body, conn = self._pending.pop(rk)
            serve_lower(rk, seq, body, conn)
            served.append(conn)
            expect.discard(rk)
        self._listener.settimeout(timeout)
        while expect:
            try:
                def accept():
                    inject.maybe_raise("p2p.accept")
                    return self._listener.accept()

                c, addr = retrying(accept, "accept")
            except (socket.timeout, TimeoutError) as e:
                raise TimeoutError(
                    f"p2p accept timed out after {timeout}s still waiting "
                    f"for processes {sorted(expect)}; raise "
                    "DCCRG_P2P_TIMEOUT if a peer is legitimately slow"
                ) from e
            c.settimeout(timeout)
            rk, seq, token, nbytes = struct.unpack(
                self._HEADER, self._recvn(c, hdr_n)
            )
            if token != self.token:
                # not a member of this job (or a stray/injected message):
                # refuse it — it must never be consumed as a contribution
                warnings.warn(
                    f"p2p message from {addr} rejected: bad job token"
                )
                c.close()
                continue
            body = self._recvn(c, nbytes, peer=rk)
            if rk not in expect:
                # a peer already in a later exchange that includes us —
                # hold its message until we reach that exchange
                if rk in self._pending:
                    c.close()
                    raise RuntimeError(
                        f"two pending p2p exchanges from process {rk}"
                    )
                self._pending[rk] = (seq, body, c)
                continue
            serve_lower(rk, seq, body, c)
            served.append(c)
            expect.discard(rk)
        # collect responses from higher ranks
        for p, seq, s in conns:
            try:
                rk, r_seq, token, nbytes = struct.unpack(
                    self._HEADER, self._recvn(s, hdr_n, peer=p)
                )
                body = self._recvn(s, nbytes, peer=p)
            except (socket.timeout, TimeoutError) as e:
                raise TimeoutError(
                    f"p2p response from process {p} (pair seq {seq}) "
                    f"timed out after {timeout}s; raise DCCRG_P2P_TIMEOUT "
                    "if the peer is legitimately slow"
                ) from e
            if rk != p or r_seq != seq or token != self.token:
                raise RuntimeError(
                    f"p2p response out of step from process {p}"
                )
            out[p] = body
            self.received_from[p] = self.received_from.get(p, 0) + nbytes
        for t in senders:
            t.join(timeout=timeout)
        for s in served + [s for _, _, s in conns]:
            s.close()
        if errors:
            raise errors[0]
        return out


def some_reduce_p2p(value, neighbor_processes, op=np.add):
    """The reference's ``Some_Reduce`` at process level
    (``dccrg_mpi_support.hpp:282-377``): symmetric point-to-point
    exchange of ``value`` with each process in ``neighbor_processes``,
    returning ``op`` over own + received values.  Collective among
    exactly those processes (each must name the others); identity with
    one controller or an empty neighbor set.  Like the reference, each
    process may pass a different value and neighbor set and gets its own
    neighborhood's result."""
    arr = np.ascontiguousarray(value)
    peers = sorted({int(p) for p in neighbor_processes})
    if process_count() == 1 or not peers:
        return arr if arr.shape else arr[()]
    t = _P2PTransport.get()
    got = t.exchange(arr.tobytes(), peers)
    stack = [arr] + [
        np.frombuffer(got[p], dtype=arr.dtype).reshape(arr.shape)
        for p in sorted(got)
    ]
    return op.reduce(np.stack(stack), axis=0)


def some_reduce(grid, per_device_values, device: int, op=np.add, hood_id=None):
    """Reduce only among a device and its halo peers — the reference's
    neighbor-only point-to-point reduce (``Some_Reduce``), whose peer set
    here comes from the halo schedule instead of explicit rank lists.

    Under multi-controller, each member process's OWN devices'
    contributions travel point-to-point among exactly the processes
    owning member devices — transport parity with the reference, not
    just value parity.  Every controller (member or not) assembles the
    full member value list and reduces it in ascending DEVICE order, so
    float results are bitwise identical everywhere (a per-process
    partial-then-merge would associate differently on each controller).
    A controller owning no member device computes from its replicated
    metadata view (per-device metadata is replicated by design) without
    joining the exchange."""
    peers = halo_peers(grid, device, hood_id)
    vals = np.asarray(per_device_values)
    members = np.unique(np.concatenate([[device], peers])).astype(np.int64)
    if process_count() == 1:
        return op.reduce(vals[members], axis=0)
    import jax

    # EVERY controller reaches the transport bootstrap (a global
    # collective on first use) before any neighbor-only exchange
    transport = _P2PTransport.get()
    me = jax.process_index()
    owner_proc = np.asarray([
        grid.mesh.devices.flat[int(d)].process_index for d in members
    ])
    mine = members[owner_proc == me]
    member_procs = sorted({int(p) for p in owner_proc} - {me})
    if not len(mine) or not member_procs:
        return op.reduce(vals[members], axis=0)
    # ship (member device ids, values) so peers can slot contributions
    # into the canonical ascending-device order
    payload = (np.uint64(len(mine)).tobytes()
               + mine.astype(np.int64).tobytes()
               + np.ascontiguousarray(vals[mine]).tobytes())
    got = transport.exchange(payload, member_procs)
    by_device = {int(d): vals[int(d)] for d in mine}
    item = vals[members[0]]
    for body in got.values():
        k = int(np.frombuffer(body[:8], np.uint64)[0])
        devs = np.frombuffer(body[8:8 + 8 * k], np.int64)
        peer_vals = np.frombuffer(
            body[8 + 8 * k:], dtype=item.dtype
        ).reshape((k,) + item.shape)
        for d, v in zip(devs, peer_vals):
            by_device[int(d)] = v
    # explicit check (not an assert: under python -O a missing
    # contribution must still fail, never silently reduce over fewer
    # members), and the reduce iterates the member list itself so an
    # EXTRA stray contribution cannot widen the reduction either
    missing = {int(d) for d in members} - set(by_device)
    if missing:
        raise RuntimeError(
            f"some_reduce missing contributions for devices "
            f"{sorted(missing)}"
        )
    ordered = np.stack([by_device[int(d)] for d in members])  # ascending
    return op.reduce(ordered, axis=0)
