"""Back-compat phase timers — a shim over the ``obs`` metrics registry.

The original 67-line ``PhaseTimers`` grew into ``dccrg_tpu.obs``
(structured counters/gauges/histograms + thread-safe, re-entrant phase
spans); this module keeps the old surface alive:

* ``timers`` — the process-wide default, now a view over ``obs.metrics``
  so phases recorded by the instrumented seams (``epoch.build``,
  ``halo.exchange``, ...) appear in ``timers.report()`` unchanged;
* ``PhaseTimers()`` — an isolated registry with the old API
  (``phase``/``report``/``reset``/``total``/``count``/``enabled``).

The old implementation double-counted a ``phase("x")`` nested inside
``phase("x")`` (both spans added their wall time); the obs registry
counts only the outermost span per thread, and is lock-protected.
"""
from __future__ import annotations

from contextlib import contextmanager

from ..obs.registry import MetricsRegistry
from ..obs.registry import metrics as _global_metrics

__all__ = ["PhaseTimers", "timers"]


class PhaseTimers:
    """The pre-obs timer API, delegating to a :class:`MetricsRegistry`."""

    def __init__(self, registry: MetricsRegistry | None = None):
        self._registry = (
            registry if registry is not None else MetricsRegistry()
        )

    @property
    def enabled(self) -> bool:
        return self._registry.enabled

    @enabled.setter
    def enabled(self, value: bool) -> None:
        self._registry.enabled = bool(value)

    def phase(self, name: str):
        return self._registry.phase(name)

    def report(self) -> dict:
        return self._registry.report()["phases"]

    def reset(self):
        self._registry.reset()

    # legacy raw accessors: {name: seconds} / {name: completions}
    @property
    def total(self) -> dict:
        return {n: rec["total_s"] for n, rec in self.report().items()}

    @property
    def count(self) -> dict:
        return {n: rec["count"] for n, rec in self.report().items()}


#: process-wide default registry (a view over ``obs.metrics``)
timers = PhaseTimers(registry=_global_metrics)


@contextmanager
def jax_trace(log_dir: str):
    """Capture a jax.profiler trace around a region (view with
    TensorBoard / xprof) — kept for back-compat; ``obs.profile_trace``
    is the full form (adds per-phase TraceAnnotation spans)."""
    from ..obs.trace import profile_trace

    with profile_trace(log_dir, annotate=True):
        yield
