"""Lightweight per-phase timers.

The reference has no tracing layer (timing lives in its workloads via
``chrono``, e.g. examples/game_of_life.cpp:116-146); SURVEY.md flags this
as a gap to fill.  This registry times named phases (grid rebuilds, halo
exchanges, solver iterations) with negligible overhead and can hand its
spans to ``jax.profiler`` traces when deeper inspection is needed.
"""
from __future__ import annotations

import time
from collections import defaultdict
from contextlib import contextmanager

__all__ = ["PhaseTimers", "timers"]


class PhaseTimers:
    def __init__(self):
        self.total = defaultdict(float)
        self.count = defaultdict(int)
        self.enabled = True

    @contextmanager
    def phase(self, name: str):
        if not self.enabled:
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.total[name] += dt
            self.count[name] += 1

    def report(self) -> dict:
        return {
            name: {
                "total_s": round(self.total[name], 6),
                "count": self.count[name],
                "mean_s": round(self.total[name] / max(self.count[name], 1), 6),
            }
            for name in sorted(self.total)
        }

    def reset(self):
        self.total.clear()
        self.count.clear()


#: process-wide default registry
timers = PhaseTimers()


@contextmanager
def jax_trace(log_dir: str):
    """Capture a jax.profiler trace around a region (view with
    TensorBoard / xprof) — the deep-inspection hook SURVEY.md §5 calls for
    on top of the phase timers."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
