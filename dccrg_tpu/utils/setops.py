"""Fast host-side set operations for epoch rebuilds.

Every structural mutation (AMR commit, load balance) ends in "rebuild all
derived state" (reference ``dccrg.hpp`` §3.4/3.5 tails), which here is
dominated by deduplicating large (a, b) integer pair sets — ghost
requirement pairs, symmetric adjacency edges, inverse neighbor relations.
``np.unique(..., axis=0)`` sorts rows through a void dtype and is the
single biggest cost at scale; packing each pair into one uint64 key and
sorting with the native OpenMP-parallel kernel
(``native/neighbor_kernels.cpp::sort_unique_u64``) is ~10-40x faster.
Numpy remains the transparent fallback.
"""
from __future__ import annotations

import numpy as np

from ..native import native_sort_unique_u64

__all__ = [
    "unique_u64",
    "unique_pairs",
    "csr_take",
    "counts_to_start",
    "ragged_arange",
]


def ragged_arange(lengths: np.ndarray) -> np.ndarray:
    """Concatenated ``arange(l)`` for each l in ``lengths`` — the rank of
    every element within its group (vectorized)."""
    lengths = np.asarray(lengths, dtype=np.int64)
    total = int(lengths.sum())
    if total == 0:
        return np.zeros(0, dtype=np.int64)
    starts = np.cumsum(lengths) - lengths
    return np.arange(total, dtype=np.int64) - np.repeat(starts, lengths)


def unique_u64(keys: np.ndarray) -> np.ndarray:
    """Sorted unique values of a uint64 array.  ``keys`` may be clobbered."""
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    out = native_sort_unique_u64(keys)
    if out is None:
        return np.unique(keys)
    return out


def unique_pairs(a: np.ndarray, b: np.ndarray, b_base: int) -> tuple[np.ndarray, np.ndarray]:
    """Sorted unique (a, b) pairs, returned as two arrays.

    ``b`` values must lie in [0, b_base).  Keys pack as
    ``a << ceil_log2(b_base) | b`` when that fits 64 bits — shift/mask
    pack and unpack are several times faster than u64 multiply/divide at
    the tens-of-millions-of-pairs scale of epoch rebuilds.  (Rounding the
    base up to a power of two keeps the key order identical to
    ``a * b_base + b``: both sort by a then b.)
    """
    a = np.asarray(a)
    b = np.asarray(b)
    shift = max(int(b_base) - 1, 1).bit_length()
    a_max = int(a.max()) if len(a) else 0
    if a_max >= (1 << (63 - shift)):
        # packing would overflow: fall back to row-wise unique (stack in a
        # common integer dtype — mixed int64/uint64 would promote to
        # float64 and corrupt values above 2^53)
        pairs = np.unique(
            np.stack(
                [a.astype(np.uint64), b.astype(np.uint64)], axis=1
            ),
            axis=0,
        )
        return pairs[:, 0].astype(np.int64), pairs[:, 1].astype(np.int64)
    sh = np.uint64(shift)
    keys = (a.astype(np.uint64) << sh) | b.astype(np.uint64)
    keys = unique_u64(keys)
    mask = np.uint64((1 << shift) - 1)
    return (keys >> sh).astype(np.int64), (keys & mask).astype(np.int64)


def counts_to_start(counts_at: np.ndarray, n: int) -> np.ndarray:
    """CSR start array (n+1) from occurrence indices (bincount-based —
    much faster than ``np.add.at``)."""
    start = np.zeros(n + 1, dtype=np.int64)
    if len(counts_at):
        start[1:] = np.bincount(counts_at, minlength=n)
    np.cumsum(start, out=start)
    return start


def csr_take(start: np.ndarray, data: np.ndarray, rows: np.ndarray) -> np.ndarray:
    """Concatenate ``data[start[r]:start[r+1]]`` for every r in ``rows``
    without a Python loop."""
    rows = np.asarray(rows, dtype=np.int64)
    counts = start[rows + 1] - start[rows]
    total = int(counts.sum())
    if total == 0:
        return data[:0]
    shift = np.repeat(start[rows] - (np.cumsum(counts) - counts), counts)
    return data[np.arange(total, dtype=np.int64) + shift]
