from .timers import PhaseTimers, timers
from .verify import verify_grid, verify_user_data

__all__ = ["PhaseTimers", "timers", "verify_grid", "verify_user_data"]
