"""Shared fast-path fallback policy for optional compiled kernels.

Every Pallas kernel in the models is an optimization layered over an
always-available XLA form.  Whether the TPU compiler accepts a kernel
can vary by hardware generation, so the first call may raise a lowering
error — but a raise can equally be the caller's own mistake (bad state
shape, wrong dtype).  The policy that distinguishes them: retry the
failing call on the fallback path first.  If the fallback also raises,
the error is the caller's and propagates unchanged; only when the
fallback succeeds is the fast path judged broken and permanently
disabled for the instance.
"""
from __future__ import annotations

import sys

__all__ = ["fallback_call"]


def fallback_call(label, fast, slow, disable, *args):
    """``fast(*args)``, falling back to ``slow(*args)`` on error.

    ``disable``: zero-arg callback run once when the fast path is judged
    broken (fallback succeeded where it raised) — mark the instance so
    subsequent calls skip straight to ``slow``.

    Multi-controller SPMD runs re-raise instead of falling back: a
    per-process switch would leave this controller issuing the slow
    path's collectives while peers (whose compiler accepted the kernel)
    run the fast path's — mismatched collective programs hang the job.
    Failing loudly matches the pre-fallback behavior; kernel eligibility
    gating is deterministic, so controllers only diverge on genuinely
    heterogeneous hardware, which needs operator attention anyway."""
    try:
        return fast(*args)
    except Exception as e:  # noqa: BLE001 - classified by the retry below
        from .collectives import process_count

        if process_count() > 1:
            raise
        try:
            out = slow(*args)
        except Exception:
            raise e  # both paths fail: the input was bad, not the kernel
        print(f"{label} disabled ({e!r:.200}); using the fallback path",
              file=sys.stderr)
        disable()
        return out
