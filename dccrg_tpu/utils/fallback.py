"""Shared fast-path fallback policy for optional compiled kernels.

Every Pallas kernel in the models is an optimization layered over an
always-available XLA form.  Whether the TPU compiler accepts a kernel
can vary by hardware generation, so the first call may raise a lowering
error — but a raise can equally be the caller's own mistake (bad state
shape, wrong dtype) or a transient runtime fault (a one-off device OOM,
a dropped tunnel).  The policy that distinguishes them: retry the
failing call on the fallback path first.  If the fallback also raises,
the error is the caller's and propagates unchanged.  If the fallback
succeeds, the fast path is disabled for the instance only when the
error is a compile/lowering rejection (which would recur on every
call): immediately for a typed ``NotImplementedError``, after two
consecutive marker-text hits otherwise (a transient error's text can
coincidentally contain a marker).  Transient runtime faults fall back
for this call only, so the kernel gets another chance next step.
"""
from __future__ import annotations

import sys
import weakref

__all__ = ["fallback_call"]

#: consecutive transient falls before a kernel is disabled anyway — a
#: deterministic runtime failure whose message lacks the permanent
#: markers (e.g. VMEM scratch exhaustion surfacing as
#: RESOURCE_EXHAUSTED) must not pay a failed fast-path attempt on every
#: step forever
_MAX_TRANSIENT_FALLS = 3

#: per-kernel-instance consecutive-transient-fall counters, keyed by the
#: object the ``disable`` callback is bound to (the model instance) so
#: the count survives across calls and dies with the instance
_transient_falls: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()

#: per-kernel-instance consecutive marker-hit counters: a *typed*
#: rejection (NotImplementedError) disables on the first hit, but the
#: substring markers below can coincidentally appear in a transient
#: runtime/RPC error's text, so marker-classified errors must recur on
#: the immediately following call before the fast path is disabled for
#: the instance lifetime
_marker_hits: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()

#: consecutive marker hits that prove the rejection deterministic
_MARKER_HITS_TO_DISABLE = 2

#: substrings that identify a deterministic compiler rejection of the
#: kernel itself — these recur on every call, so the fast path is
#: permanently disabled once they repeat.  Anything else
#: (RESOURCE_EXHAUSTED, connection drops, cancelled RPCs) is treated as
#: transient.
_PERMANENT_MARKERS = (
    "Mosaic",            # TPU kernel compiler errors are prefixed with this
    "lowering",          # jax "unsupported lowering" / "lowering rule" paths
    "Unsupported",
    "UNIMPLEMENTED",
    "does not support",
)


def _is_permanent(e: Exception) -> bool:
    """Whether the fast path's failure looks like a deterministic
    lowering / compile rejection (vs a transient runtime fault)."""
    if isinstance(e, NotImplementedError):
        return True
    text = f"{type(e).__name__}: {e}"
    return any(m in text for m in _PERMANENT_MARKERS)


def fallback_call(label, fast, slow, disable, *args):
    """``fast(*args)``, falling back to ``slow(*args)`` on error.

    ``disable``: zero-arg callback run once when the fast path is judged
    *permanently* broken (fallback succeeded where it raised with a
    compile/lowering error) — mark the instance so subsequent calls skip
    straight to ``slow``.  Transient faults fall back without disabling,
    up to ``_MAX_TRANSIENT_FALLS`` consecutive times; a fast-path
    success resets the count.  Pass a *stable* callable — a bound method
    of the kernel's owner, not a fresh per-call lambda: the transient
    counter is keyed on ``disable.__self__`` (or the callable itself),
    so a new closure every call would reset the cap each time.

    Multi-controller SPMD runs re-raise instead of falling back: a
    per-process switch would leave this controller issuing the slow
    path's collectives while peers (whose compiler accepted the kernel)
    run the fast path's — mismatched collective programs hang the job.
    Failing loudly matches the pre-fallback behavior; kernel eligibility
    gating is deterministic, so controllers only diverge on genuinely
    heterogeneous hardware, which needs operator attention anyway."""
    key = getattr(disable, "__self__", disable)
    try:
        out = fast(*args)
    except Exception as e:  # noqa: BLE001 - classified by the retry below
        from .collectives import process_count

        if process_count() > 1:
            raise
        try:
            out = slow(*args)
        except Exception:
            raise e  # both paths fail: the input was bad, not the kernel
        falls = _transient_falls.get(key, 0) + 1
        hits = _marker_hits.get(key, 0) + 1 if _is_permanent(e) else 0
        if (isinstance(e, NotImplementedError)
                or hits >= _MARKER_HITS_TO_DISABLE
                or falls >= _MAX_TRANSIENT_FALLS):
            print(f"{label} disabled ({e!r:.200}); using the fallback path",
                  file=sys.stderr)
            disable()
        else:
            _transient_falls[key] = falls
            _marker_hits[key] = hits  # 0 resets: hits must be consecutive
            print(f"{label} fell back ({falls}/{_MAX_TRANSIENT_FALLS}, "
                  f"{e!r:.200}); will retry the fast path next call",
                  file=sys.stderr)
        return out
    else:
        _transient_falls.pop(key, None)
        _marker_hits.pop(key, None)
        return out
