"""Runtime verification layer — the TPU analogue of the reference's
``#ifdef DEBUG`` machinery (``is_consistent``/``verify_neighbors``/
``verify_remote_neighbor_info``/``verify_user_data``,
``dccrg.hpp:12264-12850``).

Where the reference cross-checks replicated state between MPI ranks, the
single-controller design has one directory — so verification means checking
the *internal* consistency of every derived structure against the leaf set,
plus ghost-copy correctness of user data.  Call after mutations in tests or
debugging sessions; it is pure host-side numpy.
"""
from __future__ import annotations

import os

import numpy as np
from .collectives import fetch

__all__ = ["verify_grid", "verify_user_data", "verify_finite",
           "compare_epochs"]


def verify_finite(grid, state, spec) -> None:
    """Raise AssertionError naming the first field/device carrying a
    non-finite value in a local (owned) row — the detection oracle for
    halo NaN storms (the ``halo.nan`` injection site): a poisoned
    payload row is owned by SOME device, so scanning local rows finds
    every storm without double-reporting its ghost copies."""
    epoch = grid.epoch
    for name, (shape, dt) in spec.items():
        if not np.issubdtype(np.dtype(dt), np.floating):
            continue
        arr = fetch(state[name])
        for d in range(grid.n_devices):
            rows = epoch.row_of[epoch.local_pos[d]]
            vals = arr[d, rows]
            if not np.isfinite(vals).all():
                bad = int(np.count_nonzero(~np.isfinite(vals)))
                raise AssertionError(
                    f"non-finite values in field {name!r} on device {d} "
                    f"({bad} entries) — corrupted payload (NaN storm?)"
                )


def compare_epochs(got, want) -> None:
    """Assert two epochs carry bit-identical derived state, table by
    table — the incremental rebuild's oracle check (``got`` from
    ``parallel/epoch_delta.py``, ``want`` a fresh ``build_epoch``).
    Raises AssertionError naming the first differing table."""
    assert got.n_devices == want.n_devices
    assert got.R == want.R, (got.R, want.R)
    np.testing.assert_array_equal(got.leaves.cells, want.leaves.cells)
    np.testing.assert_array_equal(got.leaves.owner, want.leaves.owner)
    for name in ("n_local", "n_ghost", "row_of", "cell_len", "cell_level",
                 "cell_ids", "local_mask"):
        np.testing.assert_array_equal(
            getattr(got, name), getattr(want, name), err_msg=f"epoch.{name}"
        )
    for d in range(got.n_devices):
        np.testing.assert_array_equal(
            got.local_pos[d], want.local_pos[d], err_msg=f"local_pos[{d}]"
        )
        np.testing.assert_array_equal(
            got.ghost_pos[d], want.ghost_pos[d], err_msg=f"ghost_pos[{d}]"
        )
    assert (got.dense is None) == (want.dense is None), "dense flag"
    assert set(got.hoods) == set(want.hoods), "hood ids"
    for hid in want.hoods:
        g, w = got.hoods[hid], want.hoods[hid]
        np.testing.assert_array_equal(
            g.offsets, w.offsets, err_msg=f"hood {hid}: offsets"
        )
        for name in ("to_start", "to_src", "send_rows", "recv_rows",
                     "pair_counts", "inner_mask", "outer_mask", "nbr_rows",
                     "nbr_valid", "nbr_offset", "nbr_len", "nbr_slot"):
            np.testing.assert_array_equal(
                getattr(g, name), getattr(w, name),
                err_msg=f"hood {hid}: {name}",
            )
        for name in ("start", "nbr_pos", "nbr_cell", "offset", "slot"):
            np.testing.assert_array_equal(
                getattr(g.lists, name), getattr(w.lists, name),
                err_msg=f"hood {hid}: lists.{name}",
            )


def verify_grid(grid, check_two_to_one: bool = True) -> None:
    """Raise AssertionError on any internal inconsistency.

    With ``DCCRG_EPOCH_VERIFY=1`` additionally rebuilds the epoch from
    scratch and asserts the live one (possibly delta-patched after
    AMR/LB) matches it table for table — the incremental-rebuild oracle
    run at every verification point."""
    leaves = grid.leaves
    epoch = grid.epoch
    N = len(leaves)

    if os.environ.get("DCCRG_EPOCH_VERIFY", "0") != "0":
        from ..parallel.epoch import build_epoch
        from ..parallel.shapes import epoch_shape_hints

        # the oracle rebuild takes the live epoch's shapes as hints:
        # bucket choice is idempotent against its own result, so a
        # well-formed epoch is reproduced exactly (hysteresis included)
        # while any table corruption still trips the comparison
        compare_epochs(epoch, build_epoch(
            grid.mapping, grid.topology, leaves, grid.n_devices,
            grid.neighborhoods,
            uniform_geometry=grid._uniform_geometry(),
            shape_hints=epoch_shape_hints(epoch),
        ))

    # --- directory invariants (is_consistent)
    assert (np.diff(leaves.cells) > 0).all(), "leaf ids not sorted/unique"
    assert leaves.cells.dtype == np.uint64
    assert (leaves.owner >= 0).all() and (leaves.owner < grid.n_devices).all()
    lvl = grid.mapping.get_refinement_level(leaves.cells)
    assert (lvl >= 0).all(), "non-existing id in leaf set"

    # leaves must partition the domain: total index-volume matches
    ln = grid.mapping.get_cell_length_in_indices(leaves.cells).astype(object)
    vol = int(sum(int(v) ** 3 for v in ln))
    nx, ny, nz = grid.mapping.length_in_indices
    assert vol == nx * ny * nz, "leaves do not tile the domain"

    # --- row bookkeeping
    for d in range(grid.n_devices):
        lp = epoch.local_pos[d]
        assert (leaves.owner[lp] == d).all()
        np.testing.assert_array_equal(epoch.row_of[lp], np.arange(len(lp)))
        gp = epoch.ghost_pos[d]
        assert (leaves.owner[gp] != d).all(), "ghost of a local cell"

    for hid, hood in epoch.hoods.items():
        _verify_hood(grid, hood, lvl, check_two_to_one, hid)


def _verify_hood(grid, hood, lvl, check_two_to_one, hid):
    leaves = grid.leaves
    epoch = grid.epoch
    N = len(leaves)
    lists = hood.lists
    counts = np.diff(lists.start)
    src = np.repeat(np.arange(N), counts)

    # neighbor entries reference existing leaves
    assert (lists.nbr_pos >= 0).all() and (lists.nbr_pos < N).all()

    # 2:1 balance (the reference's max_ref_lvl_diff == 1 invariant)
    if check_two_to_one and len(src):
        diff = np.abs(lvl[src] - lvl[lists.nbr_pos])
        assert diff.max() <= 1, f"2:1 violation in hood {hid}"

    # neighbors_to is the exact inverse of neighbors_of
    pairs_of = set(zip(src.tolist(), lists.nbr_pos.tolist()))
    src_to = np.repeat(np.arange(N), np.diff(hood.to_start))
    pairs_to = set(zip(hood.to_src.tolist(), src_to.tolist()))
    assert pairs_to == pairs_of, f"neighbors_to not inverse in hood {hid}"

    # send/recv schedules pairwise consistent (remote-info symmetry)
    D = grid.n_devices
    scratch = epoch.R - 1
    for i in range(D):
        for j in range(D):
            s = hood.send_rows[i, j]
            r = hood.recv_rows[j, i]
            ns = int((s != scratch).sum())
            nr = int((r != scratch).sum())
            assert ns == nr == hood.pair_counts[i, j], (i, j, hid)
            if ns:
                sent_cells = epoch.cell_ids[i, s[:ns]]
                recv_cells = epoch.cell_ids[j, r[:ns]]
                np.testing.assert_array_equal(sent_cells, recv_cells)

    # inner/outer partition covers exactly the local cells
    both = hood.inner_mask & hood.outer_mask
    assert not both.any()
    np.testing.assert_array_equal(
        hood.inner_mask | hood.outer_mask, epoch.local_mask
    )


def verify_user_data(grid, state, spec, hood_id=None) -> None:
    """Ghost copies must be bit-identical to their owner rows after an
    exchange (the BASELINE halo guarantee), and field shapes/dtypes must
    match the spec."""
    epoch = grid.epoch
    for name, (shape, dt) in spec.items():
        arr = fetch(state[name])
        assert arr.shape[:2] == (grid.n_devices, epoch.R), name
        assert arr.shape[2:] == tuple(shape), name

    refreshed = grid.update_copies_of_remote_neighbors(state, hood_id)
    for name in spec:
        arr = fetch(refreshed[name])
        for d in range(grid.n_devices):
            gp = epoch.ghost_pos[d]
            if not len(gp):
                continue
            rows = epoch.rows_on_device(d, gp)
            own_dev = epoch.leaves.owner[gp]
            own_row = epoch.row_of[gp]
            np.testing.assert_array_equal(
                arr[d, rows], arr[own_dev, own_row],
                err_msg=f"ghost mismatch in field {name} on device {d}",
            )
