"""Version-compat seam for the jax surface this package touches.

The package targets the current ``jax.shard_map`` API (``check_vma``
keyword, top-level export).  Older jaxlibs that the deployment image may
pin ship the same machinery as ``jax.experimental.shard_map.shard_map``
with the ``check_rep`` spelling — one import seam keeps every call site
on the new vocabulary instead of scattering try/excepts through the
kernels.
"""
from __future__ import annotations

try:  # jax >= 0.5: top-level export, check_vma keyword
    from jax import shard_map as _shard_map

    _CHECK_KW = "check_vma"
except ImportError:  # older jax: experimental module, check_rep keyword
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    if check_vma is not None:
        kwargs[_CHECK_KW] = check_vma
    elif _CHECK_KW == "check_rep":
        # old jax's replication checker has no rule for while/fori loops
        # (it aborts whole-run kernels); it is a checker only, results
        # are unaffected, so default it off there.  New jax keeps its
        # own default when the caller does not specify.
        kwargs[_CHECK_KW] = False
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )
