#!/usr/bin/env python
"""Microbenchmarks mirroring the reference's runtime-printed speed tests:

- geometry query speed (coord->cell, cell->center) — the analogue of
  tests/geometry/cartesian_grid_speed.cpp and
  stretched_cartesian_grid_speed.cpp
- refinement throughput (cells refined/s through the full commit
  pipeline) — the analogue of tests/refine/scalability.cpp

Prints one JSON line per metric.  Host-side work: runs the same anywhere
(the cell-id algebra and AMR commit are host components by design).

Usage: python benchmarks/microbench.py [--n 1000000] [--refine-length 32]
"""
import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np


def bench_geometry(n: int):
    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.geometry.stretched import StretchedCartesianGeometry

    g = (
        Grid()
        .set_initial_length((64, 64, 64))
        .set_maximum_refinement_level(3)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0, 1.0, 1.0),
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    rng = np.random.default_rng(0)
    coords = rng.uniform(0.0, 64.0, size=(n, 3))
    cells = g.get_cells()
    ids = rng.choice(cells, size=n)

    t0 = time.perf_counter()
    found = g.geometry.get_cell(0, coords)
    t_coord = time.perf_counter() - t0
    assert (found > 0).all()

    t0 = time.perf_counter()
    centers = g.geometry.get_center(ids)
    t_center = time.perf_counter() - t0
    assert np.isfinite(centers).all()

    for name, secs in (("coord_to_cell", t_coord), ("cell_to_center", t_center)):
        print(json.dumps({
            "metric": f"geometry_{name}_queries_per_sec",
            "value": round(n / secs, 1),
            "unit": "queries/s",
        }))

    bounds = [np.linspace(0.0, 64.0, 65) ** 1.1 for _ in range(3)]
    gs = (
        Grid()
        .set_initial_length((64, 64, 64))
        .set_geometry(StretchedCartesianGeometry, coordinates=bounds)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    coords = rng.uniform(0.0, float(bounds[0][-1]), size=(n, 3))
    t0 = time.perf_counter()
    found = gs.geometry.get_cell(0, coords)
    t_s = time.perf_counter() - t0
    assert (found > 0).all()
    print(json.dumps({
        "metric": "stretched_geometry_coord_to_cell_queries_per_sec",
        "value": round(n / t_s, 1),
        "unit": "queries/s",
    }))


def bench_refinement(length: int):
    from dccrg_tpu import Grid, make_mesh

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    cells = g.get_cells()
    t0 = time.perf_counter()
    for c in cells:
        g.refine_completely(int(c))
    created = g.stop_refining()
    secs = time.perf_counter() - t0
    print(json.dumps({
        "metric": "refinement_cells_created_per_sec",
        "value": round(len(created) / secs, 1),
        "unit": "cells/s",
        "detail": {"refined": len(cells), "created": len(created), "secs": round(secs, 3)},
    }))

    leaves = g.get_cells()
    t0 = time.perf_counter()
    for c in leaves:
        g.unrefine_completely(int(c))
    g.stop_refining()
    removed = g.get_removed_cells()
    secs = time.perf_counter() - t0
    print(json.dumps({
        "metric": "unrefinement_cells_removed_per_sec",
        "value": round(len(removed) / secs, 1),
        "unit": "cells/s",
        "detail": {"requested": len(leaves), "removed": len(removed), "secs": round(secs, 3)},
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--refine-length", type=int, default=32)
    args = ap.parse_args()
    bench_geometry(args.n)
    bench_refinement(args.refine_length)


if __name__ == "__main__":
    main()
