#!/usr/bin/env python
"""Microbenchmarks mirroring the reference's runtime-printed speed tests:

- geometry query speed (coord->cell, cell->center) — the analogue of
  tests/geometry/cartesian_grid_speed.cpp and
  stretched_cartesian_grid_speed.cpp
- refinement throughput (cells refined/s through the full commit
  pipeline) — the analogue of tests/refine/scalability.cpp

Prints one JSON line per metric.  Host-side work: runs the same anywhere
(the cell-id algebra and AMR commit are host components by design).

Usage: python benchmarks/microbench.py [--n 1000000] [--refine-length 32]
"""
import argparse
import json
import os
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import jax

if __name__ == "__main__":
    # host-side measurements must not depend on (or hang with) an
    # accelerator tunnel; force the CPU backend like tests/conftest.py —
    # but only when run AS the script: bench.py's on-chip battery
    # children import pieces of this module (pic_setup,
    # halo_overlap_summary) and must keep the backend the tunnel gave
    # them, not get silently flipped to CPU by an import side effect
    jax.config.update("jax_platforms", "cpu")

import numpy as np


def bench_geometry(n: int):
    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.geometry.stretched import StretchedCartesianGeometry

    g = (
        Grid()
        .set_initial_length((64, 64, 64))
        .set_maximum_refinement_level(3)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0, 1.0, 1.0),
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    rng = np.random.default_rng(0)
    coords = rng.uniform(0.0, 64.0, size=(n, 3))
    cells = g.get_cells()
    ids = rng.choice(cells, size=n)

    t0 = time.perf_counter()
    found = g.geometry.get_cell(0, coords)
    t_coord = time.perf_counter() - t0
    assert (found > 0).all()

    t0 = time.perf_counter()
    centers = g.geometry.get_center(ids)
    t_center = time.perf_counter() - t0
    assert np.isfinite(centers).all()

    for name, secs in (("coord_to_cell", t_coord), ("cell_to_center", t_center)):
        print(json.dumps({
            "metric": f"geometry_{name}_queries_per_sec",
            "value": round(n / secs, 1),
            "unit": "queries/s",
        }))

    bounds = [np.linspace(0.0, 64.0, 65) ** 1.1 for _ in range(3)]
    gs = (
        Grid()
        .set_initial_length((64, 64, 64))
        .set_geometry(StretchedCartesianGeometry, coordinates=bounds)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    coords = rng.uniform(0.0, float(bounds[0][-1]), size=(n, 3))
    t0 = time.perf_counter()
    found = gs.geometry.get_cell(0, coords)
    t_s = time.perf_counter() - t0
    assert (found > 0).all()
    print(json.dumps({
        "metric": "stretched_geometry_coord_to_cell_queries_per_sec",
        "value": round(n / t_s, 1),
        "unit": "queries/s",
    }))


def bench_refinement(length: int):
    from dccrg_tpu import Grid, make_mesh

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_maximum_refinement_level(1)
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    cells = g.get_cells()
    t0 = time.perf_counter()
    for c in cells:
        g.refine_completely(int(c))
    created = g.stop_refining()
    secs = time.perf_counter() - t0
    print(json.dumps({
        "metric": "refinement_cells_created_per_sec",
        "value": round(len(created) / secs, 1),
        "unit": "cells/s",
        "detail": {"refined": len(cells), "created": len(created), "secs": round(secs, 3)},
    }))

    leaves = g.get_cells()
    t0 = time.perf_counter()
    for c in leaves:
        g.unrefine_completely(int(c))
    g.stop_refining()
    removed = g.get_removed_cells()
    secs = time.perf_counter() - t0
    print(json.dumps({
        "metric": "unrefinement_cells_removed_per_sec",
        "value": round(len(removed) / secs, 1),
        "unit": "cells/s",
        "detail": {"requested": len(leaves), "removed": len(removed), "secs": round(secs, 3)},
    }))

    # the same storms through the vectorized bulk request APIs
    # (identical queue semantics; what adaptation drivers use)
    cells = g.get_cells()
    t0 = time.perf_counter()
    g.refine_completely_many(cells)
    created = g.stop_refining()
    secs = time.perf_counter() - t0
    print(json.dumps({
        "metric": "bulk_refinement_cells_created_per_sec",
        "value": round(len(created) / secs, 1),
        "unit": "cells/s",
        "detail": {"requested": len(cells), "created": len(created),
                   "secs": round(secs, 3)},
    }))
    leaves = g.get_cells()
    t0 = time.perf_counter()
    g.unrefine_completely_many(leaves)
    g.stop_refining()
    removed = g.get_removed_cells()
    secs = time.perf_counter() - t0
    print(json.dumps({
        "metric": "bulk_unrefinement_cells_removed_per_sec",
        "value": round(len(removed) / secs, 1),
        "unit": "cells/s",
        "detail": {"requested": len(leaves), "removed": len(removed),
                   "secs": round(secs, 3)},
    }))


def bench_checkpoint(length: int):
    """Million-cell checkpoint round trip (reference save_grid_data /
    load_grid_data, dccrg.hpp:1089-1716) — payload packing must be
    offset-indexed scatter, not per-cell Python."""
    import os
    import tempfile

    from dccrg_tpu import Grid, make_mesh
    from dccrg_tpu.io.checkpoint import save_grid_data

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    spec = {"rho": ((), np.float32), "mom": ((3,), np.float32)}
    state = g.new_state(spec)
    cells = g.get_cells()
    rho = np.sin(cells.astype(np.float64)).astype(np.float32)
    state = g.set_cell_data(state, "rho", cells, rho)
    n = len(cells)
    tmpdir = tempfile.TemporaryDirectory()
    path = os.path.join(tmpdir.name, "bench.dc")

    from dccrg_tpu.io.checkpoint import start_loading_grid_data

    t0 = time.perf_counter()
    save_grid_data(g, state, path, spec)
    t_save = time.perf_counter() - t0
    t0 = time.perf_counter()
    loader = start_loading_grid_data(path, spec, n_devices=1)
    t_structure = time.perf_counter() - t0
    t0 = time.perf_counter()
    while loader.continue_loading_grid_data():
        pass
    g2, state2, _ = loader.finish_loading_grid_data()
    t_payload = time.perf_counter() - t0
    np.testing.assert_array_equal(g2.get_cell_data(state2, "rho", cells), rho)
    file_mb = os.path.getsize(path) / 2**20
    tmpdir.cleanup()
    print(json.dumps({
        "metric": "checkpoint_roundtrip_cells_per_sec",
        "value": round(n / (t_save + t_structure + t_payload), 1),
        "unit": "cells/s",
        "detail": {
            "n_cells": n,
            "save_s": round(t_save, 3),
            # grid re-initialization (epoch/neighbor tables) — paid by any
            # 1M-cell grid build, not a property of the file format
            "load_structure_s": round(t_structure, 3),
            # payload read + unpack + device scatter (the format's cost)
            "load_payload_s": round(t_payload, 3),
            "file_mb": round(file_mb, 1),
        },
    }))


def bench_epoch_rebuild(length: int = 64):
    """Full derived-state rebuild (neighbor lists, inverse lists, halo
    schedules, gather tables, iteration masks) — the host-side cost every
    AMR commit and load balance pays (reference: the tails of
    dccrg.hpp:3461-3485 / 3741-4147)."""
    from dccrg_tpu import Grid, make_mesh

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_neighborhood_length(1)
        .initialize(mesh=make_mesh(n_devices=1))
    )
    n = length**3
    # time the rebuild itself (balance_load skips it when no cell moves,
    # which is guaranteed on the single device this may run on)
    t0 = time.perf_counter()
    g._rebuild()
    secs = time.perf_counter() - t0
    print(json.dumps({
        "metric": "epoch_rebuild_cells_per_sec",
        "value": round(n / secs, 1),
        "unit": "cells/s",
        "detail": {"n_cells": n, "hood": 26, "secs": round(secs, 3)},
    }))


def bench_epoch_churn(length: int = 48,
                      fractions=(0.002, 0.005, 0.01, 0.05), seed: int = 0):
    """Randomized refine/unrefine storms on a refined ball: full
    ``build_epoch`` vs incremental ``build_epoch_delta`` wall time over
    a storm-size sweep (ISSUE 3's acceptance workload).  Storms are
    spatially clustered (a random sub-ball), the shape real AMR churn
    takes — a tracked feature refines where it is, not uniformly at
    random.  Every incremental epoch is asserted table-for-table
    identical to the full build before its timing is reported.

    ``touched_fraction`` in the detail is the delta path's own closure
    accounting (added + removed + one-hood-radius survivors): a storm
    REFINING f of the cells touches ~9f after children and closure
    expansion, and the path falls back above
    ``DCCRG_EPOCH_DELTA_MAX_FRACTION`` (default 25%) of the grid."""
    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
    from dccrg_tpu.amr.refinement import commit_adaptation
    from dccrg_tpu.parallel.epoch import build_epoch
    from dccrg_tpu.parallel.epoch_delta import build_epoch_delta
    from dccrg_tpu.utils.verify import compare_epochs

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_neighborhood_length(1)
        .set_maximum_refinement_level(2)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / length,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    rng = np.random.default_rng(seed)
    ids = g.get_cells()
    ctr = g.geometry.get_center(ids)
    g.refine_completely_many(ids[np.linalg.norm(ctr - 0.5, axis=1) < 0.2])
    g.stop_refining()

    def full(g):
        return build_epoch(
            g.mapping, g.topology, g.leaves, g.n_devices, g.neighborhoods,
            uniform_geometry=g._uniform_geometry(),
        )

    for frac in fractions:
        ids = g.get_cells()
        n_cells = len(ids)
        ctr = g.geometry.get_center(ids)
        rr = np.linalg.norm(ctr - rng.uniform(0.3, 0.7, 3), axis=1)
        storm = ids[rr < np.quantile(rr, frac)]
        lvl = g.mapping.get_refinement_level(storm)
        # randomized mix: refine what can refine, unrefine a slice of
        # what is already fine
        g.refine_completely_many(storm[lvl < 2])
        fine = storm[lvl == 2]
        if len(fine):
            g.unrefine_completely_many(fine[: max(1, len(fine) // 4)])
        old = g.epoch
        commit_adaptation(g)
        t_delta, t_full = [], []
        e_delta = e_full = None
        touched0 = obs.metrics.counter_value(
            "epoch.delta_cells_touched") or 0
        for _ in range(3):
            t0 = time.perf_counter()
            e_delta = build_epoch_delta(
                old, g.leaves, g.n_devices, g.neighborhoods,
                uniform_geometry=g._uniform_geometry(),
            )
            t_delta.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            e_full = full(g)
            t_full.append(time.perf_counter() - t0)
        touched = ((obs.metrics.counter_value("epoch.delta_cells_touched")
                    or 0) - touched0) // 3
        fell_back = e_delta is None
        if not fell_back:
            compare_epochs(e_delta, e_full)  # bit-identical, always
        g.epoch = e_full
        g._halo_cache = {}
        g._unrefine_cache = None
        d, f = float(np.median(t_delta)), float(np.median(t_full))
        print(json.dumps({
            "metric": f"epoch_churn_speedup_{frac:g}",
            "value": round(f / d, 2) if not fell_back else 1.0,
            "unit": "x (full/delta)",
            "detail": {
                "n_cells": n_cells,
                "storm_cells": int(len(storm)),
                "storm_fraction": round(len(storm) / n_cells, 4),
                "touched_cells": int(touched),
                "touched_fraction": round(touched / max(len(g.leaves), 1), 4),
                "delta_s": round(d, 3),
                "full_s": round(f, 3),
                "fell_back": fell_back,
                "native": os.environ.get("DCCRG_TPU_NATIVE", "1") != "0",
            },
        }))


def churn_compile_summary(length: int = 12, cycles: int = 6, seed: int = 0,
                          n_devices: int = 1) -> dict:
    """Rebuild→first-step latency + cumulative kernel compiles across a
    churn storm sweep (ISSUE 5's acceptance workload), importable so
    ``bench.py`` can fold it into BENCH_DETAIL.json.

    Runs the same randomized refine/unrefine churn twice — shape buckets
    + executable cache ON (the default) vs forced-exact shapes
    (``DCCRG_EPOCH_BUCKETS=0``, fresh per-epoch shapes) — and reports,
    per cycle, the wall time from committing the structural change to
    the first model step completing, plus the cumulative trace count.
    With sticky shapes every post-warmup cycle should re-dispatch cached
    executables (near-zero compile cost); with exact shapes every cycle
    retraces."""
    import jax

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models import Advection
    from dccrg_tpu.parallel.exec_cache import trace_counts

    def run_variant(bucketed: bool) -> dict:
        prev = os.environ.get("DCCRG_EPOCH_BUCKETS")
        os.environ["DCCRG_EPOCH_BUCKETS"] = "1" if bucketed else "0"
        try:
            g = (
                Grid()
                .set_initial_length((length, length, length))
                .set_neighborhood_length(1)
                .set_periodic(True, True, True)
                .set_maximum_refinement_level(2)
                .set_geometry(
                    CartesianGeometry,
                    start=(0.0, 0.0, 0.0),
                    level_0_cell_length=(1.0 / length,) * 3,
                )
                .initialize(mesh=make_mesh(n_devices=n_devices))
            )
            rng = np.random.default_rng(seed)
            ids = g.get_cells()
            ctr = g.geometry.get_center(ids)
            g.refine_completely_many(
                ids[np.linalg.norm(ctr - 0.5, axis=1) < 0.25]
            )
            g.stop_refining()
            adv = Advection(g, dtype=np.float32, allow_dense=False)
            state = adv.initialize_state()
            dt = np.float32(0.25 * adv.max_time_step(state))
            state = adv.step(state, dt)
            jax.block_until_ready(state["density"])

            lat, compiles, steps_s = [], [], []
            for _ in range(cycles):
                # volume-balanced storm: every refined family is offset
                # by an unrefined one, so the churn exercises rebuilds
                # without monotonic growth (real AMR tracks a feature;
                # it does not inflate the grid 25% per commit)
                ids = g.get_cells()
                lvl = g.mapping.get_refinement_level(ids)
                coarse = ids[lvl < 2]
                pick = rng.choice(len(coarse), size=min(6, len(coarse)),
                                  replace=False)
                g.refine_completely_many(coarse[pick])
                fine = ids[lvl == 2]
                if len(fine):
                    # whole families only, so the unrefine volume really
                    # lands (a lone sibling request cannot commit)
                    parents = np.unique(g.mapping.get_parent(fine))
                    sibs = g.mapping.get_all_children(parents)
                    whole = np.isin(sibs, fine).all(axis=1)
                    fams = sibs[whole]
                    if len(fams):
                        fpick = rng.choice(len(fams),
                                           size=min(6, len(fams)),
                                           replace=False)
                        g.unrefine_completely_many(
                            fams[fpick].reshape(-1)
                        )
                c0 = sum(trace_counts().values())
                t0 = time.perf_counter()
                g.stop_refining()
                adv = Advection(g, dtype=np.float32, allow_dense=False)
                state = adv.initialize_state()
                state = adv.step(state, dt)
                jax.block_until_ready(state["density"])
                lat.append(time.perf_counter() - t0)
                compiles.append(sum(trace_counts().values()) - c0)
                # steady-state step time (post-compile)
                t0 = time.perf_counter()
                state = adv.step(state, dt)
                jax.block_until_ready(state["density"])
                steps_s.append(time.perf_counter() - t0)
            return {
                "rebuild_to_first_step_s": [round(v, 4) for v in lat],
                "compiles_per_cycle": compiles,
                "steady_step_s": [round(v, 5) for v in steps_s],
                "total_compiles": int(sum(compiles)),
                "n_cells": int(len(g.get_cells())),
            }
        finally:
            if prev is None:
                os.environ.pop("DCCRG_EPOCH_BUCKETS", None)
            else:
                os.environ["DCCRG_EPOCH_BUCKETS"] = prev

    out = {
        "length": length,
        "cycles": cycles,
        "n_devices": n_devices,
        "bucketed": run_variant(True),
        "exact_shapes": run_variant(False),
    }
    b, e = out["bucketed"], out["exact_shapes"]
    out["warm_latency_ratio"] = round(
        float(np.median(e["rebuild_to_first_step_s"][1:]))
        / max(float(np.median(b["rebuild_to_first_step_s"][1:])), 1e-9), 2,
    )
    return out


def bench_churn_compile(length: int = 12, cycles: int = 6):
    """Print the :func:`churn_compile_summary` sweep as a bench metric:
    value = warm-cycle latency advantage of sticky shapes (exact-shape
    rebuild→first-step time over bucketed+cached)."""
    s = churn_compile_summary(length=length, cycles=cycles)
    print(json.dumps({
        "metric": "epoch_churn_rebuild_to_first_step_speedup",
        "value": s["warm_latency_ratio"],
        "unit": "x (exact/bucketed, median warm cycle)",
        "detail": s,
    }))


def elastic_summary(length: int = 6, seed: int = 0) -> dict:
    """The cost of elasticity (ISSUE 8): rescale latency from
    checkpoint-commit to the first post-rescale step, split cold vs
    warm persistent-compile-cache, importable so ``bench.py`` folds it
    into ``detail.telemetry.elastic``.

    Four legs on a refined advection grid: full → half → full are the
    FIRST landings of a checkpoint-replayed grid at each device count
    (cold: every landing compiles), then half → full repeats both
    landings with the persistent compilation cache primed (warm:
    ``epoch.recompiles`` stays 0, compiles served from disk).  Requires
    ``DCCRG_COMPILE_CACHE_DIR`` in the environment (the bench child
    sets a temp dir) for the warm legs to actually warm — without it
    every leg reports cold and ``cache_enabled`` is False.
    """
    import tempfile

    import jax

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
    from dccrg_tpu.models import Advection
    from dccrg_tpu.parallel.exec_cache import persistent_cache_dir
    from dccrg_tpu.resilience import rescale

    spec = {k: ((), np.float32)
            for k in ("density", "vx", "vy", "vz")}

    def build():
        g = (
            Grid()
            .set_initial_length((length, length, length))
            .set_neighborhood_length(0)
            .set_periodic(True, True, True)
            .set_maximum_refinement_level(1)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / length,) * 3,
            )
            .initialize(mesh=make_mesh())
        )
        rng = np.random.default_rng(seed)
        ids = np.sort(g.get_cells())
        for cid in rng.choice(ids, size=max(1, len(ids) // 6),
                              replace=False):
            g.refine_completely(int(cid))
        g.stop_refining()
        adv = Advection(g, dtype=np.float32, allow_dense=False)
        st = adv.initialize_state()
        ids = np.sort(g.get_cells())
        st = adv.set_cell_data(st, "density", ids,
                               rng.uniform(1, 2, len(ids))
                               .astype(np.float32))
        st = g.update_copies_of_remote_neighbors(st)
        return g, adv, st

    def totals():
        rep = obs.metrics.report()
        return (sum(rep["counters"].get("epoch.recompiles", {})
                    .values()),
                sum(rep["counters"].get("epoch.warm_compiles", {})
                    .values()))

    def leg(g, st, target, lineage_dir):
        r0, w0 = totals()
        res = rescale(g, st, spec, target, directory=lineage_dir,
                      user_header=b"bench")
        adv2 = Advection(res.grid, dtype=np.float32, allow_dense=False)
        st2 = adv2.initialize_state()
        ids2 = np.sort(res.grid.get_cells())
        st2 = adv2.set_cell_data(
            st2, "density", ids2,
            np.asarray(res.grid.get_cell_data(res.state, "density",
                                              ids2)))
        st2 = res.grid.update_copies_of_remote_neighbors(st2)
        dt = np.float32(0.25 * adv2.max_time_step(st2))
        t0 = time.perf_counter()
        out = adv2.step(st2, dt)
        jax.block_until_ready(out["density"])
        first_step = time.perf_counter() - t0
        r1, w1 = totals()
        return res.grid, st2, {
            "direction": res.direction,
            "n_devices": res.n_devices_after,
            "commit_s": round(res.commit_s, 4),
            "reland_s": round(res.reland_s, 4),
            "first_step_s": round(first_step, 4),
            "commit_to_first_step_s": round(
                res.commit_s + res.reland_s + first_step, 4),
            "recompiles": int(r1 - r0),
            "warm_compiles": int(w1 - w0),
        }

    g, adv, st = build()
    dt = np.float32(0.25 * adv.max_time_step(st))
    st = adv.step(st, dt)
    jax.block_until_ready(st["density"])
    full = g.n_devices
    half = max(1, full // 2)
    with tempfile.TemporaryDirectory() as td:
        g, st, cold_down = leg(g, st, half, td)   # first landing at half
        g, st, cold_up = leg(g, st, full, td)     # first replayed landing
        g, st, warm_down = leg(g, st, half, td)   # cache primed from here
        g, st, warm_up = leg(g, st, full, td)
    return {
        "length": length,
        "full_devices": full,
        "half_devices": half,
        "cache_enabled": persistent_cache_dir() is not None,
        "cold_down": cold_down,
        "cold_up": cold_up,
        "warm_down": warm_down,
        "warm_up": warm_up,
    }


def ensemble_summary(length: int = 4, steps: int = 16,
                     sizes=(1, 64, 256), ks=(1, 4, 16),
                     seed: int = 0) -> dict:
    """Scenario-multiplexing throughput (ISSUE 9 + 11):
    scenarios·steps/sec per chip for cohort sizes ``sizes`` at deep-
    dispatch depths ``ks`` vs solo stepping, importable so ``bench.py``
    folds it into ``detail.telemetry.ensemble``.

    One GoL grid on the general gather path (the representative
    runtime-argument form); ``B`` independent initial conditions
    admitted into one cohort and stepped through the single compiled
    cohort body, ``k`` interior steps per host dispatch (ISSUE 11's
    deep dispatch — the ``fori_loop`` bodies pay the host round-trip
    once per k steps).  ``solo`` is the same model's own step loop —
    the baseline a tenant would get with the hardware to itself.
    ``amortization`` is the cohort's scenarios·steps/sec over solo's.
    Each (B, k) cell also reports the measured per-member cohort
    memory (``hbm_bytes_per_member`` — broadcast-shared tables counted
    once) beside the pre-ISSUE-11 stacked-tables equivalent, and a
    small oracle-armed round per k reports verify check/mismatch
    counts (``verify``) so the throughput table never outruns the
    bit-identity anchor."""
    import jax

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models import GameOfLife
    from dccrg_tpu.serve import Scenario, Scheduler

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / length,) * 3,
        )
        .initialize(mesh=make_mesh())
    )
    g.stop_refining()
    gol = GameOfLife(g, allow_dense=False)
    cells = g.get_cells()
    rng = np.random.default_rng(seed)

    def fresh_state():
        return gol.new_state(
            alive_cells=cells[rng.random(len(cells)) < 0.3]
        )

    def sync(sched):
        for cohort in sched.cohorts.values():
            jax.block_until_ready(cohort._state)

    # solo baseline: the model's own step loop, one scenario
    state = fresh_state()
    s = gol.step(state)
    jax.block_until_ready(s["is_alive"])          # warm the compile
    t0 = time.perf_counter()
    s = state
    for _ in range(steps):
        s = gol.step(s)
    jax.block_until_ready(s["is_alive"])
    solo_s = (time.perf_counter() - t0) / steps
    chips = max(g.n_devices, 1)
    solo_rate = 1.0 / max(solo_s, 1e-12) / chips

    out: dict = {
        "model": "gol",
        "n_devices": g.n_devices,
        "n_cells": int(len(cells)),
        "steps": steps,
        "ks": [int(k) for k in ks],
        "solo_step_s": round(solo_s, 6),
        "solo_scenario_steps_per_s_per_chip": round(solo_rate, 1),
        "cohorts": {},
        "verify": {},
    }
    for B in sizes:
        ent: dict = {"k": {}}
        for k in ks:
            sched = Scheduler(steps_per_dispatch=k)
            iters = max(1, steps // k)
            for i in range(B):
                sched.submit(Scenario(gol, fresh_state(),
                                      k * (iters + 1), tenant=f"t{i}"))
            sched.admit()
            sched.step_once()                 # warm the depth-k body
            sync(sched)
            t0 = time.perf_counter()
            for _ in range(iters):
                sched.step_once()
            sync(sched)
            elapsed = time.perf_counter() - t0
            rate = B * k * iters / max(elapsed, 1e-12) / chips
            cohort = next(iter(sched.cohorts.values()))
            ent["k"][str(k)] = {
                "dispatch_s": round(elapsed / iters, 6),
                "step_s": round(elapsed / (iters * k), 6),
                "scenarios_steps_per_s_per_chip": round(rate, 1),
                "amortization_vs_solo": round(
                    rate / max(solo_rate, 1e-12), 2),
                "hbm_bytes_per_member": cohort.member_hbm_bytes(),
                "hbm_bytes_per_member_stacked_tables":
                    cohort.member_hbm_bytes_stacked_tables(),
                "shared_tables": bool(cohort.shared_args),
            }
        # headline row per cohort size = its deepest dispatch
        deepest = ent["k"][str(max(ks))]
        ent.update({
            "cohort_step_s": deepest["step_s"],
            "scenarios_steps_per_s_per_chip":
                deepest["scenarios_steps_per_s_per_chip"],
            "amortization_vs_solo": deepest["amortization_vs_solo"],
        })
        out["cohorts"][str(B)] = ent
    # oracle sanity per depth: a tiny verified round (the bit-identity
    # anchor must hold at every k the sweep reports numbers for)
    def _verify_totals() -> tuple:
        rep = _registry_report()
        return tuple(
            int(sum(rep["counters"].get(name, {}).values()))
            for name in ("ensemble.verify_checks",
                         "ensemble.verify_mismatches")
        )

    for k in ks:
        c0, m0 = _verify_totals()
        sched = Scheduler(steps_per_dispatch=k, verify=True)
        for i in range(2):
            sched.submit(Scenario(gol, fresh_state(), 2 * k,
                                  tenant=f"v{i}"))
        sched.run()
        c1, m1 = _verify_totals()
        out["verify"][str(k)] = {"checks": c1 - c0,
                                 "mismatches": m1 - m0}
    return out


def wide_halo_summary(length: int = 6, steps: int = 16, B: int = 16,
                      gs=(2, 4), ks=(4, 16), seed: int = 0) -> dict:
    """Exchange amortization sweep (ISSUE 14): scenarios·steps/sec per
    chip for wide-halo cohort bodies (ONE depth-g exchange per g
    interior steps) vs the legacy per-step-exchange bodies, over ghost
    depths ``gs`` × dispatch depths ``ks``, importable so ``bench.py``
    folds it into the on-chip battery.

    Each g gets its own grid (``set_neighborhood_length(g)`` fixes the
    ghost-zone depth) with GoL on a radius-1 Moore sub-hood, so the
    wide budget is exactly g; dispatches run ``cohort.step(k)``
    directly so k past the budget exercises the multi-block form
    (``ceil(k/g)`` exchanges).  The legacy variant is the SAME grid
    and cohort shape with ``DCCRG_ENSEMBLE_WIDE=0`` — the measured
    difference is purely exchange amortization.  Each cell reports the
    cumulative ``halo.exchanges_per_step`` ratio beside the rates; a
    tiny oracle-armed round per g keeps the sweep honest."""
    import os

    import jax

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models import GameOfLife
    from dccrg_tpu.parallel import halo
    from dccrg_tpu.serve import Scenario, Scheduler

    moore = [(i, j, k) for i in (-1, 0, 1) for j in (-1, 0, 1)
             for k in (-1, 0, 1) if (i, j, k) != (0, 0, 0)]
    rng = np.random.default_rng(seed)
    out: dict = {"model": "gol", "B": int(B), "steps": int(steps),
                 "gs": [int(g) for g in gs], "ks": [int(k) for k in ks],
                 "g": {}, "verify": {}}

    def run_cells(gol, wide: bool) -> dict:
        cells = gol.grid.get_cells()
        res: dict = {}
        for k in ks:
            sched = Scheduler()
            iters = max(1, steps // k)
            for i in range(B):
                sched.submit(Scenario(
                    gol,
                    gol.new_state(alive_cells=cells[
                        rng.random(len(cells)) < 0.3]),
                    k * (iters + 1), tenant=f"t{i}"))
            sched.admit()
            cohort = next(iter(sched.cohorts.values()))
            cohort.step(k)                 # warm the (k, g) body
            jax.block_until_ready(cohort._state)
            halo._amortization.clear()
            t0 = time.perf_counter()
            for _ in range(iters):
                cohort.step(k)
            jax.block_until_ready(cohort._state)
            elapsed = time.perf_counter() - t0
            chips = max(gol.grid.n_devices, 1)
            rep = _registry_report()
            res[str(k)] = {
                "dispatch_s": round(elapsed / iters, 6),
                "scenarios_steps_per_s_per_chip": round(
                    B * k * iters / max(elapsed, 1e-12) / chips, 1),
                "exchanges_per_step": rep["gauges"].get(
                    "halo.exchanges_per_step", {}).get("model=gol"),
                "wide": bool(cohort._wide is not None) if wide
                else False,
            }
        return res

    prev = os.environ.get("DCCRG_ENSEMBLE_WIDE")
    for gdepth in gs:
        grid = (
            Grid()
            .set_initial_length((length, length, length))
            .set_neighborhood_length(int(gdepth))
            .set_periodic(True, True, True)
            .set_geometry(
                CartesianGeometry,
                start=(0.0, 0.0, 0.0),
                level_0_cell_length=(1.0 / length,) * 3,
            )
            .initialize(mesh=make_mesh())
        )
        grid.stop_refining()
        grid.add_neighborhood(7, moore)
        try:
            os.environ.pop("DCCRG_ENSEMBLE_WIDE", None)
            wide_gol = GameOfLife(grid, hood_id=7, allow_dense=False)
            wide_cells = run_cells(wide_gol, wide=True)
            os.environ["DCCRG_ENSEMBLE_WIDE"] = "0"
            legacy_gol = GameOfLife(grid, hood_id=7, allow_dense=False)
            legacy_cells = run_cells(legacy_gol, wide=False)
        finally:
            if prev is None:
                os.environ.pop("DCCRG_ENSEMBLE_WIDE", None)
            else:
                os.environ["DCCRG_ENSEMBLE_WIDE"] = prev
        ent: dict = {"k": {}}
        for k in ks:
            w, l = wide_cells[str(k)], legacy_cells[str(k)]
            ent["k"][str(k)] = {
                "wide": w, "legacy": l,
                "speedup": round(
                    w["scenarios_steps_per_s_per_chip"]
                    / max(l["scenarios_steps_per_s_per_chip"], 1e-12),
                    3),
            }
        out["g"][str(gdepth)] = ent
        # oracle-armed round at this depth: the sweep's numbers must
        # never outrun the owned-row bit-identity anchor
        c0 = _counter_total("ensemble.verify_checks")
        m0 = _counter_total("ensemble.verify_mismatches")
        vs = Scheduler(steps_per_dispatch=min(int(gdepth), 4),
                       verify=True)
        cells = wide_gol.grid.get_cells()
        for i in range(2):
            vs.submit(Scenario(
                wide_gol,
                wide_gol.new_state(alive_cells=cells[
                    rng.random(len(cells)) < 0.3]),
                2 * int(gdepth), tenant=f"v{i}"))
        vs.run()
        out["verify"][str(gdepth)] = {
            "checks": _counter_total("ensemble.verify_checks") - c0,
            "mismatches":
                _counter_total("ensemble.verify_mismatches") - m0,
        }
    return out


def bench_wide_halo(length: int = 6, steps: int = 16):
    """Print the :func:`wide_halo_summary` sweep as a bench metric: the
    deepest (g, k) cell's wide-over-legacy throughput ratio."""
    s = wide_halo_summary(length=length, steps=steps)
    gmax, kmax = str(max(int(g) for g in s["gs"])), \
        str(max(int(k) for k in s["ks"]))
    cell = s["g"][gmax]["k"][kmax]
    print(json.dumps({
        "bench": "wide_halo",
        "metric": "wide_over_legacy_speedup",
        "value": cell["speedup"],
        "detail": s,
    }))


def _counter_total(name: str) -> int:
    rep = _registry_report()
    return int(sum(rep["counters"].get(name, {}).values()))


def _registry_report() -> dict:
    from dccrg_tpu import obs

    return obs.metrics.report()


def bench_ensemble(length: int = 4, steps: int = 16):
    """Print the :func:`ensemble_summary` sweep as a bench metric:
    value = scenarios·steps/sec/chip at the largest cohort size and
    deepest dispatch — the serving-throughput headline beside
    cell-updates/sec."""
    s = ensemble_summary(length=length, steps=steps)
    largest = max(s["cohorts"], key=int)
    deepest = max(s["ks"])
    print(json.dumps({
        "metric": "ensemble_scenarios_steps_per_sec_per_chip",
        "value": s["cohorts"][largest]["scenarios_steps_per_s_per_chip"],
        "unit": (f"scenarios*steps/s/chip (cohort {largest}, "
                 f"k={deepest})"),
        "detail": s,
    }))


def cost_summary(length: int = 4, steps: int = 16, B: int = 8,
                 k: int = 4, seed: int = 0) -> dict:
    """Model-priced vs EMA-only scheduling (ISSUE 17): the same
    deadline-mixed burst served twice — once with the fleet cost model
    pricing ``select_k``'s slack clamp (``DCCRG_COST_MODEL=1``, the
    default) and once on the pre-cost cohort-local EMA path
    (``DCCRG_COST_MODEL=0``) — importable so ``bench.py`` folds it into
    ``detail.telemetry.cost``.  The switch is read per call, so the two
    arms flip mid-process with no respawn.

    Per arm: a warm wave compiles the depth-k body (and, armed, trains
    the exact ``(model, sig, k, g, W)`` key past
    ``DCCRG_COST_MIN_SAMPLES``), a solo pace round measures per-step
    seconds, then a burst of ``B`` scenarios — half with deadlines
    affording roughly half their steps at the measured pace, half
    generous — runs under the deadline policy.  Reported per arm:
    deadline misses / miss rate, scenarios·steps/sec per chip, and the
    answering prediction's level and sample count.  The acceptance
    direction: the armed arm must not miss MORE than EMA-only."""
    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models import GameOfLife
    from dccrg_tpu.obs import cost
    from dccrg_tpu.serve import Scenario, Scheduler

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / length,) * 3,
        )
        .initialize(mesh=make_mesh())
    )
    g.stop_refining()
    gol = GameOfLife(g, allow_dense=False)
    cells = g.get_cells()
    rng = np.random.default_rng(seed)

    def fresh_state():
        return gol.new_state(
            alive_cells=cells[rng.random(len(cells)) < 0.3]
        )

    chips = max(g.n_devices, 1)

    def run_arm(armed: bool) -> dict:
        os.environ["DCCRG_COST_MODEL"] = "1" if armed else "0"
        cost.model.reset()
        cost.tracker.reset()
        # warm both dispatch depths the burst can reach at the burst's
        # width: the configured k AND the depth-1 body a blown deadline
        # clamps to — otherwise the first arm pays that compile inside
        # its timed window and the arms stop being comparable
        for depth in (k, 1):
            warm = Scheduler(steps_per_dispatch=depth)
            for _ in range(max(cost.min_samples(), 4)):
                warm.submit(Scenario(gol, fresh_state(),
                                     steps if depth == k else 2,
                                     tenant="warm"))
            warm.run()
        # throwaway solo round first: the width-1 body compiles here in
        # whichever arm runs first, so both arms measure a warm pace
        for timed in (False, True):
            pace_sched = Scheduler(steps_per_dispatch=k)
            pace_sched.submit(Scenario(gol, fresh_state(), steps,
                                       tenant="pace"))
            t0 = time.perf_counter()
            pace_sched.run()
            if timed:
                pace = (time.perf_counter() - t0) / steps
        m0 = _counter_total("ensemble.deadline_miss")
        sched = Scheduler(policy="deadline", steps_per_dispatch=k)
        now = time.perf_counter()
        for i in range(B):
            tight = i % 2 == 0
            sched.submit(Scenario(
                gol, fresh_state(), steps, tenant=f"c{i % 2}",
                deadline=now + steps * pace * (0.5 if tight else 50.0),
            ))
        t0 = time.perf_counter()
        sched.run()
        elapsed = time.perf_counter() - t0
        misses = _counter_total("ensemble.deadline_miss") - m0
        est = cost.model.predict("gol") if armed else None
        return {
            "deadline_misses": int(misses),
            "miss_rate": round(misses / B, 3),
            "scenarios_steps_per_s_per_chip": round(
                B * steps / max(elapsed, 1e-12) / chips, 1),
            "elapsed_s": round(elapsed, 6),
            "pace_step_s": round(pace, 6),
            "predict_level": est.level if est is not None else None,
            "predict_n": est.n if est is not None else 0,
        }

    prev = os.environ.get("DCCRG_COST_MODEL")
    try:
        out = {
            "model": "gol",
            "n_devices": g.n_devices,
            "B": B, "k": int(k), "steps": steps,
            "armed": run_arm(True),
            "ema_only": run_arm(False),
        }
    finally:
        if prev is None:
            os.environ.pop("DCCRG_COST_MODEL", None)
        else:
            os.environ["DCCRG_COST_MODEL"] = prev
    out["miss_delta_armed_minus_ema"] = (
        out["armed"]["deadline_misses"]
        - out["ema_only"]["deadline_misses"])
    return out


def bench_cost(length: int = 4, steps: int = 16):
    """Print the :func:`cost_summary` comparison as a bench metric:
    value = deadline misses with the cost model armed (the unit string
    carries the EMA-only count — the acceptance is armed <= EMA)."""
    s = cost_summary(length=length, steps=steps)
    print(json.dumps({
        "metric": "cost_model_deadline_misses",
        "value": s["armed"]["deadline_misses"],
        "unit": (f"misses of {s['B']} (EMA-only "
                 f"{s['ema_only']['deadline_misses']}, k={s['k']})"),
        "detail": s,
    }))


def halo_overlap_summary(steps: int = 20, length: int = 8, reps: int = 3,
                         seed: int = 0, profile: bool = True) -> dict:
    """Eager vs host-split vs fused split-phase stepping per model
    (gol / advection / vlasov) on the current device mesh (ISSUE 7),
    importable so ``bench.py`` can fold it into BENCH_DETAIL.json
    (``detail.telemetry.halo_overlap``).

    Three forms of advancing one step:

    * ``eager`` — the blocking step (ghost exchange fused into the
      model's program);
    * ``host_split`` — the source paper's host-orchestrated pattern
      (``start_remote_neighbor_copies`` / eager step / ``wait``): one
      EXTRA host-level refresh rides along per step, so this column is
      an upper bound showing the dispatch overhead the fused form
      removes;
    * ``fused`` — the model's ``overlap=True`` step: start → interior →
      finish → boundary inside ONE compiled program.

    ``overlap_fraction`` per model is MEASURED (not inferred): a
    profiled fused round merged against the device timeline
    (``obs.merge_profile``), None when the backend leaves no execution
    lines."""
    import jax

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
    from dccrg_tpu.models import Advection, GameOfLife, Vlasov

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_neighborhood_length(1)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_load_balancing_method("RCB")
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / length,) * 3,
        )
        .initialize(mesh=make_mesh())
    )
    ids = g.get_cells()
    ctr = g.geometry.get_center(ids)
    g.refine_completely_many(ids[np.linalg.norm(ctr - 0.5, axis=1) < 0.3])
    g.stop_refining()
    g.balance_load()
    rng = np.random.default_rng(seed)
    cells = g.get_cells()

    def median_step(step, state):
        s = step(state)
        jax.block_until_ready(s)                      # warm the compiles
        times = []
        for _ in range(reps):
            s = state
            t0 = time.perf_counter()
            for _ in range(steps):
                s = step(s)
            jax.block_until_ready(s)
            times.append((time.perf_counter() - t0) / steps)
        return float(np.median(times))

    def measured_overlap(step, state, model):
        """Profiled fused round -> overlap.fraction{model=...}."""
        import tempfile

        obs.enable()
        obs.enable_timeline()

        def stamped(s):
            t0 = time.perf_counter()
            out = step(s)
            obs.metrics.phase_add("halo.start", time.perf_counter() - t0)
            t0 = time.perf_counter()
            jax.block_until_ready(out)
            obs.metrics.phase_add("halo.exchange",
                                  time.perf_counter() - t0)
            return out

        try:
            with tempfile.TemporaryDirectory() as td:
                with obs.profile_trace(td):
                    s = state
                    for _ in range(4):
                        s = stamped(s)
                _merged, summary = obs.merge_profile(
                    td, extra_labels={"model": model}
                )
            if not summary["device_evidence"]:
                return None
            return summary["overlap"]["halo"]["fraction"]
        except Exception:  # noqa: BLE001 — measurement, never the bench
            return None

    out: dict = {"n_devices": g.n_devices, "steps": steps,
                 "n_cells": int(len(cells)),
                 "halo_backend": g.halo().backend, "models": {}}

    for model in ("gol", "advection", "vlasov"):
        if model == "gol":
            eager = GameOfLife(g, allow_dense=False)
            fused = GameOfLife(g, overlap=True)
            alive0 = cells[rng.random(len(cells)) < 0.3]
            state_e = eager.new_state(alive_cells=alive0)
            state_f = fused.new_state(alive_cells=alive0)
            field = "is_alive"
            step_e = eager.step
            step_f = fused.step
        elif model == "advection":
            eager = Advection(g, dtype=np.float32, allow_dense=False)
            fused = Advection(g, dtype=np.float32, allow_dense=False,
                              overlap=True)
            state_e = eager.initialize_state()
            state_f = fused.initialize_state()
            dt = np.float32(0.4 * eager.max_time_step(state_e))
            field = "density"
            step_e = lambda s: eager.step(s, dt)
            step_f = lambda s: fused.step(s, dt)
        else:
            eager = Vlasov(g, nv=2, dtype=np.float32)
            fused = Vlasov(g, nv=2, dtype=np.float32, overlap=True)
            state_e = eager.initialize_state()
            state_f = fused.initialize_state()
            dt = np.float32(0.5 * eager.max_time_step())
            field = "f"
            step_e = lambda s, _e=eager, _dt=dt: _e.step(s, _dt)
            step_f = lambda s, _f=fused, _dt=dt: _f.step(s, _dt)

        def step_split(s, _step=step_e, _field=field):
            fields = {_field: s[_field]}
            handle = g.start_remote_neighbor_copy_updates(fields)
            interior = _step(s)
            fields = g.wait_remote_neighbor_copy_updates(fields, handle)
            return {**interior, **fields, _field: interior[_field]}

        rec = {
            "eager_step_s": round(median_step(step_e, state_e), 6),
            "host_split_step_s": round(median_step(step_split, state_e),
                                       6),
            "fused_step_s": round(median_step(step_f, state_f), 6),
        }
        rec["fused_vs_eager"] = round(
            rec["eager_step_s"] / max(rec["fused_step_s"], 1e-12), 3
        )
        if profile:
            rec["overlap_fraction"] = measured_overlap(
                step_f, state_f, model
            )
        out["models"][model] = rec
    return out


def bench_halo_overlap(steps: int = 20, length: int = 8):
    """Print the :func:`halo_overlap_summary` sweep as a bench metric:
    value = the worst fused-vs-eager step ratio across models (>= 1.0
    means the fused split-phase step regressed nothing)."""
    s = halo_overlap_summary(steps=steps, length=length)
    ratios = [m["fused_vs_eager"] for m in s["models"].values()]
    print(json.dumps({
        "metric": "halo_overlap_fused_vs_eager",
        "value": round(min(ratios), 3),
        "unit": "x (eager/fused step latency, worst model)",
        "detail": s,
    }))


def pic_setup(n_particles: int, length: int = 32, *, max_ref: int = 0,
              refine_ball: float | None = None,
              balance_method: str | None = None, seed: int = 0):
    """Shared PIC benchmark fixture (also used by the root bench.py):
    periodic grid, uniformly-random particles, capacity from the actual
    max occupancy (Poisson tails overflow any fixed multiple of the
    mean — doubled for drift during the run), and the rotating velocity
    field of the reference's particle test.  Returns
    ``(particles_model, initial_points, velocity_field)``.

    ``refine_ball``: refine every cell within that radius of the domain
    center (requires ``max_ref >= 1``); ``balance_method``: run a
    ``balance_load`` under the given partitioner after refinement — the
    reference's actual particle use case (AMR + non-block ownership,
    ``tests/particles/simple.cpp``)."""
    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models.particles import Particles

    g = (
        Grid()
        .set_initial_length((length, length, length))
        .set_neighborhood_length(1)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(max_ref)
        .set_load_balancing_method(balance_method or "RCB")
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / length,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=1))
    )
    if refine_ball is not None:
        ids = g.get_cells()
        ctr = g.geometry.get_center(ids)
        rr = np.linalg.norm(ctr - 0.5, axis=1)
        for cid in ids[rr < refine_ball]:
            g.refine_completely(int(cid))
        g.stop_refining()
    if balance_method is not None:
        g.balance_load()
    rng = np.random.default_rng(seed)
    pts = rng.uniform(0.0, 1.0, size=(n_particles, 3))
    occ = np.bincount(g.leaves.position(g.get_existing_cell(pts)))
    pc = Particles(g, max_particles_per_cell=2 * int(occ.max()))
    vel = pc.velocity_field(
        lambda c: np.stack(
            [0.5 - c[:, 1], c[:, 0] - 0.5, np.full(len(c), 0.05)], axis=-1
        )
    )
    return pc, pts, vel


def bench_particles(n_particles: int, length: int = 32):
    """PIC pushes/s INCLUDING migration (ghost exchange + re-bucketing) —
    the full per-step cost of the reference's particle test
    (tests/particles/simple.cpp:285-294), not just the position update."""
    pc, pts, vel = pic_setup(n_particles, length)

    t0 = time.perf_counter()
    state = pc.new_state(pts)
    t_bucket = time.perf_counter() - t0
    steps = 5
    import jax

    state = pc.run(state, 1, velocity=vel, dt=0.2 / length)  # compile
    jax.block_until_ready(state["particles"])
    t0 = time.perf_counter()
    state = pc.run(state, steps, velocity=vel, dt=0.2 / length)
    jax.block_until_ready(state["particles"])
    secs = time.perf_counter() - t0
    assert pc.count(state) == n_particles
    assert int(np.asarray(state.get("overflow", 0))) == 0
    print(json.dumps({
        "metric": "pic_pushes_per_sec_incl_migration",
        "value": round(n_particles * steps / secs, 1),
        "unit": "pushes/s",
        "detail": {
            "n_particles": n_particles,
            "steps": steps,
            "secs": round(secs, 3),
            "initial_bucket_s": round(t_bucket, 3),
            "grid": [length] * 3,
        },
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=1_000_000)
    ap.add_argument("--refine-length", type=int, default=32)
    ap.add_argument("--checkpoint-length", type=int, default=100)
    ap.add_argument("--particles", type=int, default=1_000_000)
    ap.add_argument("--churn-length", type=int, default=48,
                    help="level-0 edge for the epoch-churn sweep "
                         "(48^3 + refined ball > 130k cells)")
    args = ap.parse_args()
    bench_geometry(args.n)
    bench_refinement(args.refine_length)
    bench_checkpoint(args.checkpoint_length)
    bench_epoch_rebuild()
    bench_epoch_churn(args.churn_length)
    bench_churn_compile()
    bench_halo_overlap()
    bench_ensemble()
    bench_wide_halo()
    bench_cost()
    bench_particles(args.particles)


if __name__ == "__main__":
    main()
