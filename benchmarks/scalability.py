#!/usr/bin/env python
"""Scalability sweep: throughput vs device count — the analogue of the
reference's tests/scalability family and its sweep driver
(tests/scalability/run_tests.py:27-39), which runs ``mpirun -np N`` for a
range of N.  Here N is a virtual CPU device count (the same mechanism the
test suite uses) unless run on a real multi-chip mesh.

Usage: python benchmarks/scalability.py [gol|advection] [--devices 1 2 4 8]
"""
import argparse
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))


def run_sweep(workload: str, counts, size: int, turns: int):
    # env vars do not reliably override a tunneled TPU platform; force the
    # virtual CPU mesh via jax.config exactly like tests/conftest.py
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", max(counts))
    import numpy as np

    from dccrg_tpu import CartesianGeometry, Grid, make_mesh
    from dccrg_tpu.models import Advection, GameOfLife

    results = []
    for n_dev in counts:
        mesh = make_mesh(n_devices=n_dev)
        if workload == "gol":
            grid = (
                Grid()
                .set_initial_length((size, size, 1))
                .set_neighborhood_length(1)
                .initialize(mesh=mesh)
            )
            gol = GameOfLife(grid)
            rng = np.random.default_rng(0)
            cells = grid.get_cells()
            state = gol.new_state(alive_cells=cells[rng.random(len(cells)) < 0.3])
            jax.block_until_ready(gol.run(state, 2))
            t0 = time.perf_counter()
            state = gol.run(state, turns)
            jax.block_until_ready(state)
            secs = time.perf_counter() - t0
            n_cells = size * size
        elif workload == "refined":
            # the reference's refined_scalability3d.cpp analogue: a
            # two-level AMR advection sweep (boxed per-level path)
            n = max(8, size // 16)
            nz = max(n_dev * 2, 8)
            grid = (
                Grid()
                .set_initial_length((n, n, nz))
                .set_neighborhood_length(0)
                .set_periodic(True, True, True)
                .set_maximum_refinement_level(1)
                .set_geometry(
                    CartesianGeometry,
                    start=(0.0, 0.0, 0.0),
                    level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / nz),
                )
                .initialize(mesh=mesh)
            )
            ids = grid.get_cells()
            c = grid.geometry.get_center(ids)
            r = np.linalg.norm(c - 0.5, axis=1)
            for cid in ids[r < 0.3]:
                grid.refine_completely(int(cid))
            grid.stop_refining()
            adv = Advection(grid, dtype=np.float32, allow_dense=False)
            state = adv.initialize_state()
            dt = np.float32(0.4 * adv.max_time_step(state))
            jax.block_until_ready(adv.run(state, 2, dt))
            t0 = time.perf_counter()
            state = adv.run(state, turns, dt)
            jax.block_until_ready(state)
            secs = time.perf_counter() - t0
            n_cells = len(grid.get_cells())
        else:
            grid = (
                Grid()
                .set_initial_length((size, size, n_dev))
                .set_neighborhood_length(0)
                .set_periodic(True, True, True)
                .set_geometry(
                    CartesianGeometry,
                    start=(0.0, 0.0, 0.0),
                    level_0_cell_length=(1.0 / size, 1.0 / size, 1.0 / n_dev),
                )
                .initialize(mesh=mesh)
            )
            adv = Advection(grid, dtype=np.float32)
            state = adv.initialize_state()
            dt = np.float32(0.4 * adv.max_time_step(state))
            jax.block_until_ready(adv.run(state, 2, dt))
            t0 = time.perf_counter()
            state = adv.run(state, turns, dt)
            jax.block_until_ready(state)
            secs = time.perf_counter() - t0
            n_cells = size * size * n_dev
        # halo traffic per count (reference sweep logs report message
        # volume alongside throughput): useful ghost bytes and actual
        # wire bytes of the general ring schedule for a one-f32-field
        # exchange, times the turn count, over the measured wall time
        halo = grid.halo(None)
        probe = {"f": np.zeros((n_dev, grid.epoch.R), np.float32)}
        useful_b = halo.bytes_moved(probe) * turns
        wire_b = halo.wire_bytes(probe) * turns
        row = {
            "devices": n_dev,
            "cells": n_cells,
            "turns": turns,
            "secs": round(secs, 4),
            "cell_updates_per_s": round(n_cells * turns / secs, 1),
            "per_device_per_s": round(n_cells * turns / secs / n_dev, 1),
            "halo_GBps": round(useful_b / secs / 1e9, 4),
            "halo_wire_GBps": round(wire_b / secs / 1e9, 4),
            "ring_distances": len(halo.ring_ks),
        }
        results.append(row)
        print(json.dumps(row))
    return results


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("workload", nargs="?", default="gol",
                    choices=["gol", "advection", "refined"])
    ap.add_argument("--devices", type=int, nargs="+", default=[1, 2, 4, 8])
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--turns", type=int, default=20)
    a = ap.parse_args()
    run_sweep(a.workload, a.devices, a.size, a.turns)
