#!/usr/bin/env python
"""Ensemble serving demo (ISSUE 9): multiplex a parameter sweep of
independent advection scenarios through one compiled executable.

Builds N same-shape grids (the bucketed-epoch discipline lands them on
one ``ShapeSignature``), gives each scenario its own randomized density
field and timestep, submits everything to the :class:`~dccrg_tpu.serve.
Ensemble`, and verifies a sampled member against solo stepping.  Run
with ``DCCRG_ENSEMBLE_VERIFY=1`` to arm the per-step oracle too.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import argparse
import time

import jax
import numpy as np

jax.config.update("jax_enable_x64", True)   # f64 density, like the tests

from dccrg_tpu import CartesianGeometry, Grid, make_mesh, obs
from dccrg_tpu.models import Advection
from dccrg_tpu.serve import Ensemble


def build_model(n, seed):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(CartesianGeometry, start=(0.0, 0.0, 0.0),
                      level_0_cell_length=(1.0 / n,) * 3)
        .initialize(mesh=make_mesh())
    )
    g.stop_refining()
    adv = Advection(g, dtype=np.float64, allow_dense=False)
    state = adv.initialize_state()
    rng = np.random.default_rng(seed)
    ids = np.sort(g.get_cells())
    state = adv.set_cell_data(state, "density", ids,
                              rng.uniform(0.5, 2.0, len(ids)))
    state = g.update_copies_of_remote_neighbors(state)
    return adv, state


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenarios", type=int, default=16)
    ap.add_argument("--cells", type=int, default=6,
                    help="level-0 edge length per scenario grid")
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    print(f"building {args.scenarios} scenarios "
          f"({args.cells}^3 cells each)...")
    sweep = [build_model(args.cells, seed)
             for seed in range(args.scenarios)]
    dt = 0.4 * sweep[0][0].max_time_step(sweep[0][1])

    ens = Ensemble()
    tickets = [
        ens.submit(adv, state, steps=args.steps, dt=dt,
                   tenant=f"user{i}")
        for i, (adv, state) in enumerate(sweep)
    ]
    t0 = time.perf_counter()
    served = ens.run()
    wall = time.perf_counter() - t0
    cohorts = list(ens.cohorts.values())
    print(f"served {served} scenario-steps in {wall:.2f}s through "
          f"{len(cohorts)} cohort(s) "
          f"(widths {[c.W for c in cohorts]})")

    # sampled member vs solo stepping — the bit-identity anchor
    adv, state = sweep[0]
    ref = state
    for _ in range(args.steps):
        ref = adv.step(ref, dt)
    same = np.array_equal(np.asarray(ref["density"]),
                          np.asarray(tickets[0].result["density"]))
    print(f"member 0 bit-identical to solo stepping: {same}")

    rep = obs.metrics.report()
    served_by = rep["counters"].get("ensemble.steps_served", {})
    print(f"tenants served: {len(served_by)}; "
          f"queue latency: "
          f"{rep['histograms']['ensemble.queue_latency']['']['mean']:.4f}s"
          f" mean")


if __name__ == "__main__":
    main()
