#!/usr/bin/env python
"""The simplest game-of-life program demonstrating basic usage — the
analogue of the reference's examples/simple_game_of_life.cpp: build a
10x10 grid, balance load, run 100 turns of a blinker and self-verify its
oscillation.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.models import GameOfLife


def main():
    grid = (
        Grid()
        .set_initial_length((10, 10, 1))
        .set_maximum_refinement_level(0)
        .set_neighborhood_length(1)
        .set_load_balancing_method("RCB")
        .initialize(mesh=make_mesh())
    )
    grid.balance_load()

    gol = GameOfLife(grid)
    state = gol.new_state(alive_cells=[54, 55, 56])

    for turn in range(1, 101):
        state = gol.step(state)
        alive = set(gol.alive_cells(state).tolist())
        assert 55 in alive, f"turn {turn}: blinker center died"
        expect = {45, 55, 65} if turn % 2 == 1 else {54, 55, 56}
        assert alive == expect, f"turn {turn}: got {alive}"

    print("PASSED")


if __name__ == "__main__":
    main()
