#!/usr/bin/env python
"""Poisson solver example — the analogue of the reference's
tests/poisson programs: solve ∇²φ = ρ on an adaptively refined grid with
the matrix-free BiCG solver and verify against the analytic solution.

With ρ = sin(2πx) the exact periodic solution is
φ = -sin(2πx) / (2π)² (up to a constant); the discrete solve must agree
to discretization order, and refining a slab of the domain must not
break it.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Poisson


def main():
    n = 16
    grid = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh())
    )
    # refine a slab in the middle of the domain
    ids = grid.get_cells()
    x = grid.geometry.get_center(ids)[:, 0]
    for cid in ids[(x > 0.4) & (x < 0.6)]:
        grid.refine_completely(int(cid))
    grid.stop_refining()

    ids = grid.get_cells()
    centers = grid.geometry.get_center(ids)
    rhs = np.sin(2 * np.pi * centers[:, 0])

    model = Poisson(grid)
    state = model.initialize_state(rhs)
    # restarts: BiCG on refined (non-normal) systems can stop early at
    # the semi-convergence rule; re-entering from the best solution
    # recovers (see Poisson.solve)
    state, residual, iterations = model.solve(
        state, max_iterations=2000, stop_residual=1e-10, restarts=3
    )

    phi = np.asarray(grid.get_cell_data(state, "solution", ids), np.float64)
    exact = -np.sin(2 * np.pi * centers[:, 0]) / (2 * np.pi) ** 2
    # remove the periodic solve's free constant (volume-weighted mean)
    vol = np.prod(grid.geometry.get_length(ids), axis=-1)
    phi = phi - (phi * vol).sum() / vol.sum()
    exact = exact - (exact * vol).sum() / vol.sum()
    err = np.abs(phi - exact).max() / np.abs(exact).max()

    print(f"{len(ids)} cells ({(grid.mapping.get_refinement_level(ids) > 0).sum()}"
          f" refined), {iterations} iterations, residual {residual:.2e}, "
          f"max rel error vs analytic {err:.3e}")
    assert err < 0.02, err     # second-order discretization at n=16
    print("PASSED")


if __name__ == "__main__":
    main()
