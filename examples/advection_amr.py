#!/usr/bin/env python
"""3-D advection with dynamic AMR and periodic load balancing — the
analogue of the reference's tests/advection/2d.cpp main loop: pre-adapt
around the density hump, then step / adapt every adapt_n / balance every
balance_n, optionally saving VTK snapshots.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import argparse

import numpy as np

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cells", type=int, default=400)
    ap.add_argument("--max-ref-lvl", type=int, default=2)
    ap.add_argument("--tmax", type=float, default=1.0)
    ap.add_argument("--adapt-n", type=int, default=1)
    ap.add_argument("--balance-n", type=int, default=25)
    ap.add_argument("--cfl", type=float, default=0.5)
    ap.add_argument("--save", type=str, default="")
    args = ap.parse_args()

    n = int(round(np.sqrt(args.cells)))
    grid = (
        Grid()
        .set_initial_length((n, n, 1))
        .set_maximum_refinement_level(args.max_ref_lvl)
        .set_neighborhood_length(0)
        .set_periodic(True, True, False)
        .set_load_balancing_method("RCB")
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n, 1.0 / n, 1.0 / n),
        )
        .initialize(mesh=make_mesh())
    )
    adv = Advection(grid, allow_dense=False)
    state = adv.initialize_state()

    # initial adaptation rounds (2d.cpp:267-289)
    for _ in range(args.max_ref_lvl):
        state = adv.check_for_adaptation(state)
        adv, state, new_cells, removed = adv.adapt_grid(state)

    t, step = 0.0, 0
    dt = adv.max_time_step(state)
    print(f"initial timestep {dt:.5f}, {grid.get_total_cells()} cells")
    while t < args.tmax:
        state = adv.step(state, args.cfl * dt)
        t += args.cfl * dt
        step += 1
        if args.adapt_n and step % args.adapt_n == 0:
            state = adv.check_for_adaptation(state)
            adv, state, _, _ = adv.adapt_grid(state)
            dt = adv.max_time_step(state)
        if args.balance_n and step % args.balance_n == 0:
            grid.balance_load()
            state = grid.remap_state(state)
            adv = Advection(grid, allow_dense=False)
            state = adv._exchange(state)
        if args.save and step % 10 == 0:
            rho = adv.get_cell_data(state, "density", grid.get_cells())
            grid.write_vtk_file(f"{args.save}_{step:05d}.vtk", scalars={"density": rho})
    print(
        f"done: {step} steps, t={t:.3f}, {grid.get_total_cells()} cells, "
        f"mass {adv.total_mass(state):.6f}"
    )


if __name__ == "__main__":
    main()
