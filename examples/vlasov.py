#!/usr/bin/env python
"""Vlasov advection example — the Vlasiator-style payload the reference
grid was built to carry (reference CREDITS:4-6): a velocity-space
distribution block f(v) per spatial cell, advected through space with
df/dt + v·∇_x f = 0.

A Maxwellian hump is placed mid-domain; after one periodic crossing time
per velocity bin the density field translates while total phase-space
mass is conserved exactly (periodic boundaries).  The step runs the
blocked fused kernel (ops/vlasov_kernel.py) on accelerators — all three
dimension-split updates in a single HBM pass.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Vlasov


def main():
    n = 16
    grid = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh())
    )
    vl = Vlasov(grid, nv=4, v_max=0.5, dtype=np.float32)
    state = vl.initialize_state(thermal_v=0.3)
    m0 = vl.total_mass(state)
    dt = np.float32(0.4 * vl.max_time_step())

    steps = 200
    state = vl.run(state, steps, dt)
    m1 = vl.total_mass(state)
    drift = abs(m1 - m0) / m0
    print(f"phase-space mass {m0:.6e} -> {m1:.6e} (rel drift {drift:.2e})")
    assert drift < 1e-5, "periodic Vlasov must conserve mass"

    rho = vl.density(state)
    print(
        f"density field: min {rho.min():.4e} max {rho.max():.4e} "
        f"({n}^3 spatial cells x {vl.B} velocity bins, "
        f"fused_block={vl._fused_block})"
    )
    print("PASSED")


if __name__ == "__main__":
    main()
