#!/usr/bin/env python
"""Checkpoint/restart example — the analogue of the reference's
tests/restart/restart_test.cpp: run an advecting density half way, save
to a .dc-style file, reload on a DIFFERENT device count, finish the run,
and verify the result is bit-identical to the uninterrupted run.
"""
import pathlib
import sys
import tempfile

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Advection


def build(n, n_devices):
    g = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(0)
        .set_periodic(True, True, True)
        .set_maximum_refinement_level(1)
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh(n_devices=n_devices))
    )
    ids = g.get_cells()
    c = g.geometry.get_center(ids)
    r = np.linalg.norm(c - 0.45, axis=1)
    for cid in ids[r < 0.25]:
        g.refine_completely(int(cid))
    g.stop_refining()
    return g


def main():
    n, total_steps, half = 8, 24, 12
    g = build(n, n_devices=4)
    adv = Advection(g)
    state = adv.initialize_state()
    dt = 0.4 * adv.max_time_step(state)

    # --- the uninterrupted run
    ref = state
    for _ in range(total_steps):
        ref = adv.step(ref, dt)
    ids = g.get_cells()
    want = np.asarray(adv.get_cell_data(ref, "density", ids))

    # --- half the run, checkpoint, reload at a different device count
    for _ in range(half):
        state = adv.step(state, dt)
    spec = {"density": adv.spec["density"]}
    with tempfile.TemporaryDirectory() as tmp:
        path = str(pathlib.Path(tmp) / "mid.dc")
        g.save_grid_data(state, path, spec, user_header=b"restart-example")
        g2, state2, header = Grid.load_grid_data(path, spec, n_devices=2)
        assert header == b"restart-example"
    assert np.array_equal(g2.get_cells(), ids), "reload reproduced the grid"

    adv2 = Advection(g2)
    resumed = adv2.initialize_state()
    resumed = {**resumed, "density": state2["density"]}
    resumed = g2.update_copies_of_remote_neighbors(resumed)
    for _ in range(total_steps - half):
        resumed = adv2.step(resumed, dt)
    got = np.asarray(adv2.get_cell_data(resumed, "density", ids))

    np.testing.assert_allclose(got, want, rtol=0, atol=0)
    print(f"PASSED: {len(ids)} cells (refined), saved at step {half} on 4 "
          f"devices, resumed on 2, bit-identical to the uninterrupted run")


if __name__ == "__main__":
    main()
