#!/usr/bin/env python
"""Particle-in-cell example — the analogue of the reference's
tests/particles/simple.cpp: particles live in cells as variable-size
payloads, are pushed through a velocity field, migrate between cells
(including across device boundaries), and survive a load balance.

Self-verifies: the particle count is conserved through pushes, rebuckets,
and a balance_load, and every particle sits in the cell containing it.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from dccrg_tpu import CartesianGeometry, Grid, make_mesh
from dccrg_tpu.models import Particles


def main():
    n = 8
    grid = (
        Grid()
        .set_initial_length((n, n, n))
        .set_neighborhood_length(1)
        .set_periodic(True, True, True)
        .set_load_balancing_method("RCB")
        .set_geometry(
            CartesianGeometry,
            start=(0.0, 0.0, 0.0),
            level_0_cell_length=(1.0 / n,) * 3,
        )
        .initialize(mesh=make_mesh())
    )

    rng = np.random.default_rng(42)
    n_particles = 5000
    model = Particles(grid, max_particles_per_cell=64)
    state = model.new_state(rng.random((n_particles, 3)))
    assert model.count(state) == n_particles

    # a rotating velocity field (vortex around the domain center)
    def vortex(centers):
        v = np.zeros_like(centers)
        v[:, 0] = -(centers[:, 1] - 0.5)
        v[:, 1] = centers[:, 0] - 0.5
        return 0.3 * v

    velocity = model.velocity_field(vortex)
    for turn in range(20):
        state = model.step(state, velocity=velocity, dt=0.05)
        assert model.count(state) == n_particles, turn

    # particles stay bucketed in the cell containing them
    for cell in grid.get_cells()[:32]:
        pts = model.particles_of(state, int(cell))
        if len(pts):
            lo = grid.geometry.get_min(np.asarray([cell], np.uint64))[0]
            hi = grid.geometry.get_max(np.asarray([cell], np.uint64))[0]
            assert ((pts >= lo) & (pts <= hi)).all(), cell

    # migration machinery survives a repartition; the per-cell velocity
    # field is epoch-shaped, so rebuild it after the balance
    grid.balance_load()
    state = model.remap(state)
    velocity = model.velocity_field(vortex)
    state = model.step(state, velocity=velocity, dt=0.05)
    assert model.count(state) == n_particles

    print(f"PASSED: {n_particles} particles, 21 pushes, load balance, "
          f"all buckets consistent")


if __name__ == "__main__":
    main()
