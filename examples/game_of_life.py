#!/usr/bin/env python
"""Game of life with throughput reporting — the analogue of the
reference's examples/game_of_life.cpp: both its overlapped
compute/transfer pattern (lines 124-138 — here the split-phase
``GameOfLife(grid, overlap=True)`` step: collective launched, inner cells
computed with no dependence on it, ghosts merged, outer cells computed)
and its min/avg/max cells/process/s report (lines 116-180).  Runs the
blocking and overlap variants back to back and reports both.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import time

import numpy as np

from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.models import GameOfLife


def main(size: int = 500, turns: int = 100):
    grid = (
        Grid()
        .set_initial_length((size, size, 1))
        .set_neighborhood_length(1)
        .set_load_balancing_method("RCB")
        .initialize(mesh=make_mesh())
    )
    grid.balance_load()

    rng = np.random.default_rng(0)
    cells = grid.get_cells()
    alive0 = cells[rng.random(len(cells)) < 0.3]

    import jax

    results = {}
    for name, overlap in (("blocking", False), ("overlap", True)):
        gol = GameOfLife(grid, overlap=overlap)
        state = gol.new_state(alive_cells=alive0)
        jax.block_until_ready(gol.step(state))  # compile
        t0 = time.perf_counter()
        state = gol.run(state, turns)
        jax.block_until_ready(state)
        secs = time.perf_counter() - t0
        results[name] = (secs, set(gol.alive_cells(state).tolist()))
        n_dev = grid.n_devices
        per_dev = [
            grid.get_local_cell_count(d) * turns / secs for d in range(n_dev)
        ]
        print(
            f"[{name}] devices: {n_dev}, grid {size}x{size}, {turns} turns "
            f"in {secs:.3f}s"
        )
        print(
            f"[{name}] cells/device/s min {min(per_dev):.3e} "
            f"avg {sum(per_dev)/n_dev:.3e} max {max(per_dev):.3e}; "
            f"total {size*size*turns/secs:.3e} cells/s"
        )
    assert results["blocking"][1] == results["overlap"][1], "physics differs!"
    print(
        f"overlap speedup: "
        f"{results['blocking'][0] / results['overlap'][0]:.3f}x"
    )


if __name__ == "__main__":
    import sys

    main(*(int(a) for a in sys.argv[1:3]))
