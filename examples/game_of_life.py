#!/usr/bin/env python
"""Game of life with throughput reporting — the analogue of the
reference's examples/game_of_life.cpp (its overlapped compute/transfer
pattern, lines 124-138, is subsumed here by the jitted step: XLA schedules
the halo collective and the local stencil for overlap automatically) and of
its min/avg/max cells/process/s report (lines 116-180).
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import time

import numpy as np

from dccrg_tpu import Grid, make_mesh
from dccrg_tpu.models import GameOfLife


def main(size: int = 500, turns: int = 100):
    grid = (
        Grid()
        .set_initial_length((size, size, 1))
        .set_neighborhood_length(1)
        .set_load_balancing_method("RCB")
        .initialize(mesh=make_mesh())
    )
    grid.balance_load()
    gol = GameOfLife(grid)

    rng = np.random.default_rng(0)
    cells = grid.get_cells()
    alive0 = cells[rng.random(len(cells)) < 0.3]
    state = gol.new_state(alive_cells=alive0)

    import jax

    jax.block_until_ready(gol.step(state))  # compile
    t0 = time.perf_counter()
    state = gol.run(state, turns)
    jax.block_until_ready(state)
    secs = time.perf_counter() - t0

    n_dev = grid.n_devices
    per_dev = [grid.get_local_cell_count(d) * turns / secs for d in range(n_dev)]
    print(f"devices: {n_dev}, grid {size}x{size}, {turns} turns in {secs:.3f}s")
    print(
        f"cells/device/s min {min(per_dev):.3e} avg {sum(per_dev)/n_dev:.3e} "
        f"max {max(per_dev):.3e}; total {size*size*turns/secs:.3e} cells/s"
    )


if __name__ == "__main__":
    import sys

    main(*(int(a) for a in sys.argv[1:3]))
