#!/usr/bin/env python
"""Poisson on a stretched Cartesian grid — the configuration the flat
voxel operator always refuses, exercising the general operator space
(reference: dccrg supports any geometry through the same per-pair
factor cache, tests/poisson/poisson_solve.hpp:716-745, with
Stretched_Cartesian_Geometry from dccrg_stretched_cartesian_geometry.hpp).

The cell boundaries follow a tanh-graded spacing (fine near the domain
center, coarse at the edges — the classic boundary-layer layout).  On
accelerator backends the solver runs the rolled static-offset
decomposition of the operator (ops/rolled_gather.py); on CPU it runs
the gather tables.  Both are the same operator: the solve must agree
with the analytic solution of ∇²φ = ρ to discretization order.

With ρ = sin(2πx) on x ∈ [0, 1] and Dirichlet boundaries φ = 0 applied
through boundary cells, the exact solution is φ = -sin(2πx)/(2π)².
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from dccrg_tpu import Grid, StretchedCartesianGeometry, make_mesh
from dccrg_tpu.models import Poisson


def graded(n, lo=0.0, hi=1.0, strength=1.5):
    """n+1 monotone boundaries on [lo, hi], clustered around the middle."""
    u = np.linspace(-1.0, 1.0, n + 1)
    x = np.tanh(strength * u) / np.tanh(strength)
    return lo + (hi - lo) * (x + 1.0) / 2.0


def main():
    n = 24
    grid = (
        Grid()
        .set_initial_length((n, 3, 3))
        .set_neighborhood_length(0)
        .set_periodic(False, True, True)
        .set_maximum_refinement_level(0)
        .set_geometry(
            StretchedCartesianGeometry,
            coordinates=[graded(n), np.linspace(0.0, 1.0, 4),
                         np.linspace(0.0, 1.0, 4)],
        )
        .initialize(mesh=make_mesh())
    )

    ids = grid.get_cells()
    centers = grid.geometry.get_center(ids)
    x = centers[:, 0]
    # first/last x-slabs are Dirichlet boundary cells holding φ = 0
    bounds = graded(n)
    boundary = (x < bounds[1]) | (x > bounds[-2])
    solve_cells = ids[~boundary]

    rhs = np.sin(2 * np.pi * x)
    model = Poisson(grid, solve_cells=solve_cells)
    path = ("rolled" if model._rolled is not None
            else "flat" if model._flat is not None else "gather")
    state = model.initialize_state(rhs)
    state, residual, iterations = model.solve(
        state, max_iterations=2000, stop_residual=1e-10, restarts=3
    )

    phi = np.asarray(grid.get_cell_data(state, "solution", ids), np.float64)
    exact = -np.sin(2 * np.pi * x) / (2 * np.pi) ** 2
    sel = ~boundary
    err = np.abs(phi - exact)[sel].max() / np.abs(exact[sel]).max()

    widths = np.diff(bounds)
    print(f"{len(ids)} cells, x-spacing {widths.min():.4f}..{widths.max():.4f}, "
          f"operator path: {path}, {iterations} iterations, "
          f"residual {residual:.2e}, max rel error vs analytic {err:.3e}")
    assert err < 0.05, err  # second-order on the graded spacing at n=24
    print("PASSED")


if __name__ == "__main__":
    main()
