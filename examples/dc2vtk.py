#!/usr/bin/env python
"""Convert a .dc checkpoint to a VTK file — the analogue of the
reference's examples/dc2vtk.cpp (VisIt/ParaView workflow,
examples/README:20-35).

The payload spec is given on the command line as name:dtype[:shape] items,
e.g.  ``dc2vtk.py run.dc out.vtk density:f8 mom:f8:3``.
"""
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

import numpy as np

from dccrg_tpu import Grid


def parse_spec(items):
    spec = {}
    for item in items:
        parts = item.split(":")
        name, dtype = parts[0], np.dtype(parts[1])
        shape = tuple(int(v) for v in parts[2:])
        spec[name] = (shape, dtype)
    return spec


def main():
    if len(sys.argv) < 4:
        sys.exit(__doc__)
    src, dst = sys.argv[1], sys.argv[2]
    spec = parse_spec(sys.argv[3:])
    grid, state, header = Grid.load_grid_data(src, spec, n_devices=1)
    cells = grid.get_cells()
    scalars = {}
    for name, (shape, _) in spec.items():
        vals = grid.get_cell_data(state, name, cells)
        if shape == ():
            scalars[name] = vals
        else:
            flat = vals.reshape(len(cells), -1)
            for i in range(flat.shape[1]):
                scalars[f"{name}_{i}"] = flat[:, i]
    grid.write_vtk_file(dst, scalars=scalars)
    print(f"wrote {dst}: {len(cells)} cells, fields {list(scalars)}")


if __name__ == "__main__":
    main()
